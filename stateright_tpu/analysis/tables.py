"""The single home of primitive/opcode classification.

Three consumers audit the sparse-engine codegen contract and each
needs to agree on what counts as "real compute", "a gather", or
"carry movement":

* the jaxpr-level kernel-lint rules (:mod:`.rules`, ``pytest -m
  lint``, ``tools/lint_kernels.py``),
* the codegen-shape tests (tests/test_codegen_shapes.py, which
  calibrated the allowed residue against the hand paxos encoding),
* the wave-wall profiler's per-HLO-category attribution
  (stateright_tpu/wavewall.py), which classifies optimized-HLO
  opcodes with the same vocabulary the round-5 device-trace analysis
  used.

Before round 7 the first two each carried a private copy of the ALU
set and the third its own opcode table; a primitive added to one and
not the others silently weakened the audit. Everything below is data
(frozensets / dicts) plus two pure classifiers so the tables cannot
drift per consumer.
"""

from __future__ import annotations

import re

# --------------------------------------------------------------------------
# jaxpr-primitive side (the lint rules and codegen-shape tests)
# --------------------------------------------------------------------------

#: elementwise/ALU primitives — a ``[N, 1]`` output from any of these
#: is real compute at 128x lane padding, the PERF.md §ordered tax.
#: (Shape-only ops — slice, reshape, broadcast, concatenate — are NOT
#: here: a ``[N, 1]`` slice from consuming a multi-lane gather row is
#: the intended sparse idiom and fuses; ``[N, 1]`` COMPUTE does not.)
ALU_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "and", "or", "xor",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "select_n", "eq", "ne", "lt", "le", "gt", "ge", "min", "max",
    "population_count", "convert_element_type", "neg", "not",
})

#: primitives that price as carry/block movement at the jaxpr level —
#: the static fingerprint of the between-stage wave wall (PERF.md
#: §wave-wall). The carry-copy-bytes estimator sums the output bytes
#: of these inside ``cond``/``switch`` branches.
CARRY_MOVE_PRIMS = frozenset({
    "concatenate", "pad", "slice", "dynamic_slice",
    "dynamic_update_slice", "copy",
})

#: minimum output bytes before a branch pad/concat counts as buffer
#: assembly rather than index plumbing (a 2-operand ``[N, 1]`` concat
#: that builds a gather index pair is the calibrated paxos residue and
#: fuses; a full-F frontier rebuild does not).
BRANCH_PAD_CONCAT_MIN_BYTES = 4096

#: axis-0 growth factor above which a branch pad/concat reads as
#: "pad small class result to full capacity" (the pre-round-6 pattern
#: the class-local dynamic_update_slice rework deleted) rather than a
#: merge-style append of comparably-sized halves.
BRANCH_PAD_CONCAT_GROWTH = 2.0

#: value-preserving unary ops a padded carry may pass through between
#: a pad/concat and its branch return (a ``.astype(...)`` or reshape
#: must not hide a peak-shape rebuild from the branch rule).
PASSTHROUGH_PRIMS = frozenset({
    "convert_element_type", "reshape", "copy", "bitcast_convert_type",
    "stop_gradient",
})

#: per-fixture budgets for the GATED carry-copy-bytes rule (round 9):
#: the total bytes the wave body's cond/switch eqns may carry as
#: branch outputs. The round-9 class collapse (slim merge cores +
#: one fetch switch per wave + SoA vkeys/plog, PERF.md §layout) took
#: the 2pc-rm3 fixture from 21 switches / 1,422,204 B to
#: 9 switches / 244,316 B. Round 10 (the incrementally-sorted
#: visited + streaming merge, PERF.md §merge-kernel) re-priced it to
#: 13 switches / 344,908 B — a deliberate, audited addition: the
#: membership v-switch returns a bool[B] mask, the visited-append
#: v-switch returns vkeys alone (the fetch switch stopped carrying
#: it — net zero there), and the parent log carries child limbs
#: again (the sorted merge destroyed the positional derivation);
#: every new branch output is still a single small mask or a single
#: resident buffer. The budget sits ~30% above the measured value so
#: incidental carry additions (a new counter lane) pass but a
#: structural regression — another full-carry switch boundary, a
#: peak-shape branch rebuild — fails the lint loudly instead of
#: silently re-inflating the wave wall. Keys are the fixture names
#: the lint driver traces (TraceCtx.encoding).
CARRY_COPY_BYTE_BUDGETS = {
    "engine-fixture(2pc-rm3)": 450_000,
    # the same wave body traced with the Pallas merge kernel (the
    # chip invocation style): identical switch structure, so the
    # same budget pins it.
    "engine-fixture(2pc-rm3,merge=pallas)": 450_000,
    # The SHARDED engine's wave body in its TRACED form (round 11,
    # registry.SHARDED_WAVE_BODY_FIXTURE): 9 switches / 153,780 B
    # measured at the fixture shapes. The per-shard mesh log's only
    # switch-carry addition is the 36 B ``swave`` row the merge stage
    # returns (9 uint32 lanes; the ``slog`` appends live OUTSIDE the
    # switches, in the body wrapper, so they price as loop-body DUS,
    # not branch carry) — i.e. the telemetry layer moved the carry
    # budget by 36 B, not by the log size. The sharded body's total
    # sits BELOW the single-chip fixture's 344,908 B because its
    # f-ladder switch carries the lean per-shard buffers (C=2^11 per
    # shard) while the dest tiles and recv buffers are wave-local
    # temporaries. Budget ~30% above measurement, same policy as the
    # rows above.
    "engine-fixture(2pc-rm3,sharded+slog)": 200_000,
}


def is_gather(primitive_name: str) -> bool:
    """The gather classification every audit shares: any primitive
    whose name contains ``gather`` (``gather``, ``dynamic_gather``,
    batched variants) — at the jaxpr level take/``x[idx]``/
    ``take_along_axis`` all lower to one of these. Cross-device
    collectives (``all_gather``) are NOT memory gathers: they classify
    through :data:`COLLECTIVE_PRIMS` and the comms rules instead."""
    return "gather" in primitive_name and not is_collective(
        primitive_name
    )


# --------------------------------------------------------------------------
# collective classification (the comms-lint rule family, round 13)
# --------------------------------------------------------------------------

#: jaxpr collective primitives → comms category. ONE home for both
#: sides of the static collective accounting: the jaxpr walk
#: (analysis/comms.py, the comms rules) classifies with this table and
#: the ``--hlo`` cross-check classifies compiled modules with
#: :data:`HLO_COLLECTIVE_OPS` below — the two vocabularies share the
#: category strings, so "jaxpr reductions == HLO all-reduces" is one
#: dict comparison, not a per-consumer mapping. ``pvary``/
#: ``axis_index`` are deliberately absent: they are axis PLUMBING
#: (replication typing / shard identity), move zero bytes, and listing
#: them here would inflate every byte total.
COLLECTIVE_PRIMS = {
    "all_to_all": "all-to-all",
    "psum": "reduction",
    "psum2": "reduction",  # newer-jax spelling of the same reduce
    "pmax": "reduction",
    "pmin": "reduction",
    "ppermute": "permute",
    "pbroadcast": "broadcast",
    "all_gather": "all-gather",
    "all_gather_invariant": "all-gather",
    "reduce_scatter": "reduce-scatter",
}

#: the collective categories whose operands must stay rank-0/tiny
#: (the ``scalar-only-reductions`` rule): a psum over a resident
#: ``[W, F]`` buffer is an accidental replication — every shard pays
#: the full buffer's all-reduce bandwidth for a value the engine only
#: ever needs element-wise on one shard.
REDUCTION_CATEGORIES = frozenset({"reduction"})

#: max elements a psum/pmax/pmin operand may carry before the
#: scalar-only-reductions rule flags it. The engines' reductions are
#: scalars and per-property vectors (property count < 32 by the
#: eventually-bits contract); 64 leaves headroom for a property-family
#: growth while still sitting orders of magnitude below any resident
#: buffer.
SCALAR_REDUCTION_MAX_ELEMS = 64

#: per-fixture allowances for the GATED ``no-all-gather`` rule: how
#: many ``all_gather`` eqns a traced comms fixture may contain.
#: Default (unlisted) is 0 — the wave path never all-gathers: visited
#: state is owner-sharded by construction and an all_gather of it is
#: the 8x traffic blow-up the rule exists to catch. A DRAIN-path
#: fixture (host-side counterexample reconstruction staging, which
#: legitimately collects shard-local logs) would register its
#: allowance here, the way step-path gathers register theirs in
#: ``EncodingSpec.max_step_gathers``. No current fixture needs one.
ALL_GATHER_ALLOWANCES: dict = {}

#: per-fixture budgets for the GATED ``comms-bytes`` rule (the comms
#: analog of CARRY_COPY_BYTE_BUDGETS): the PER-WAVE PEAK collective
#: payload — the fattest single class branch's collective bytes plus
#: any collectives outside the class switch — measured at the comms
#: fixtures' shapes (analysis/comms.py) and budgeted ~30% above, so a
#: new counter psum passes but a structural regression (a second
#: all_to_all, a buffer-sized reduction) fails loudly. Keys are the
#: comms fixture names (TraceCtx.encoding).
#:
#: Measured at S=2 (2 shards), 2pc rm=3 fixture shapes
#: (dest_tile_width=7 lanes x 4 B rows, Bd=1024):
#: * sortmerge untraced: 57,436 B — the peak class's all_to_all
#:   [2*1024, 7] u32 tile exchange (57,344 B) + 54 scalar/property
#:   psums (344 B across all classes, ~92 B in the peak branch);
#: * sortmerge traced (+slog): 57,440 B — the per-shard mesh log is
#:   never psum-collapsed (its contract), so tracing adds exactly ONE
#:   scalar psum (the global wave row's n_tot back-fill, 4 B); the
#:   shared budget pins that zero-traffic claim;
#: * hash engine: 57,424 B untraced / 57,428 traced (same all_to_all
#:   tile, no class ladder — one fixed-shape wave, 12-13 scalar
#:   psums);
#: * the reconciliation fixture (2pc rm=5 at the TRACE_r16 dryrun
#:   config, S=8): 229,472 B — all_to_all [8*1024, 7] = 229,376 B +
#:   scalar psums.
COMMS_BYTE_BUDGETS = {
    "comms(2pc-rm3,sortmerge,S2)": 75_000,
    "comms(2pc-rm3,sortmerge,S2,traced)": 75_000,
    "comms(2pc-rm3,hash,S2)": 75_000,
    "comms(2pc-rm3,hash,S2,traced)": 75_000,
    "comms(2pc-rm5,sortmerge,S8,traced)": 300_000,
    # the TIERED chunk program (round 16, stateright_tpu/tier.py):
    # the same wave body plus the commit phase's scalar psums/pmax —
    # measured per-wave peak 57,452 B vs the untiered 57,436 B
    # (+16 B = one conf psum + the h_loc pmax), same ~30% headroom
    "comms(2pc-rm3,sortmerge,S2,tiered,traced)": 75_000,
}


def is_collective(primitive_name: str) -> bool:
    """Whether a jaxpr primitive is a cross-shard collective — the
    recognition every comms rule shares. Prefix-matched for the
    all_gather family so a renamed variant (``all_gather_invariant``)
    can't slip past the no-all-gather gate unclassified."""
    return (
        primitive_name in COLLECTIVE_PRIMS
        or primitive_name.startswith("all_gather")
    )


def collective_category(primitive_name: str) -> str:
    """jaxpr collective primitive → comms category."""
    if primitive_name in COLLECTIVE_PRIMS:
        return COLLECTIVE_PRIMS[primitive_name]
    if primitive_name.startswith("all_gather"):
        return "all-gather"
    return "other-collective"


def collective_bytes(eqn) -> int:
    """Static byte price of one collective eqn: the larger of its
    operand and result payloads (an all_to_all moves its operand, an
    all_gather materializes its S-times-larger RESULT on every shard
    — max covers both directions without a per-primitive table).
    Token/unit avals price as 0 through :func:`output_bytes`."""
    in_b = sum(
        output_bytes(v.aval) for v in eqn.invars if hasattr(v, "aval")
    )
    out_b = sum(output_bytes(v.aval) for v in eqn.outvars)
    return max(in_b, out_b)


def output_bytes(aval) -> int:
    """Bytes of one jaxpr output aval (0 for abstract tokens)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


# --------------------------------------------------------------------------
# HLO-opcode side (the wave-wall profiler and the --hlo lint pass)
# --------------------------------------------------------------------------

#: dtype byte widths for HLO shape strings.
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: HLO opcode -> trace-category, the round-5 device-trace vocabulary
#: (PERF.md). Copies/transposes/converts are XLA's between-stage data
#: formatting; pad is class-quantization padding; slice/concat/
#: dynamic-(update-)slice are carry and block movement; fusion is the
#: actual stage compute.
HLO_CATEGORY = {}
for _op in ("copy", "copy-start", "copy-done", "bitcast",
            "bitcast-convert", "transpose", "reshape", "convert"):
    HLO_CATEGORY[_op] = "data formatting"
HLO_CATEGORY["pad"] = "quantization padding"
HLO_CATEGORY["dynamic-update-slice"] = "dynamic-update-slice"
for _op in ("dynamic-slice", "slice", "concatenate"):
    HLO_CATEGORY[_op] = "carry/slice movement"
HLO_CATEGORY["sort"] = "sort"
HLO_CATEGORY["gather"] = "gather"
HLO_CATEGORY["scatter"] = "scatter"
HLO_CATEGORY["fusion"] = "fusion"

#: HLO collective opcodes → the SAME comms-category vocabulary as
#: COLLECTIVE_PRIMS (one home: the --hlo collective cross-check in
#: analysis/comms.py reconciles per-category op counts across the two
#: tables). Async pairs: the ``-start`` op carries the payload and
#: counts; the ``-done`` op is completion plumbing and classifies as
#: control (counting both would double every TPU collective).
HLO_COLLECTIVE_OPS = {
    "all-to-all": "all-to-all",
    "all-to-all-start": "all-to-all",
    "all-reduce": "reduction",
    "all-reduce-start": "reduction",
    "reduce-scatter": "reduce-scatter",
    "reduce-scatter-start": "reduce-scatter",
    "all-gather": "all-gather",
    "all-gather-start": "all-gather",
    "collective-permute": "permute",
    "collective-permute-start": "permute",
    "collective-broadcast": "broadcast",
}
for _op in HLO_COLLECTIVE_OPS:
    HLO_CATEGORY[_op] = "collective"
for _op in ("all-to-all-done", "all-reduce-done", "all-gather-done",
            "reduce-scatter-done", "collective-permute-done"):
    HLO_CATEGORY[_op] = "control"
for _op in ("while", "conditional", "call", "tuple",
            "get-tuple-element", "parameter", "constant",
            "iota", "broadcast", "after-all", "partition-id",
            "replica-id"):
    HLO_CATEGORY[_op] = "control"
for _op in ("add", "subtract", "multiply", "divide", "remainder",
            "and", "or", "xor", "not", "negate", "compare",
            "select", "shift-left", "shift-right-logical",
            "shift-right-arithmetic", "popcnt", "clz",
            "maximum", "minimum", "abs", "sign", "clamp",
            "reduce", "reduce-window", "map", "exponential",
            "log", "power"):
    # XLA:CPU leaves elementwise ALU unfused where the TPU trace
    # shows loop fusions — same stage-compute category.
    HLO_CATEGORY[_op] = "elementwise compute"
del _op

#: the categories whose bytes ARE the wave wall (the carry-copy-bytes
#: estimator's HLO-level numerator).
HLO_WALL_CATEGORIES = frozenset({
    "data formatting", "quantization padding",
    "carry/slice movement", "dynamic-update-slice",
})

_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*?)\s+"
    r"([a-z][a-z0-9\-]*)\("
)


def hlo_category(opcode: str) -> str:
    """Map an HLO opcode to the trace-category vocabulary."""
    return HLO_CATEGORY.get(opcode, "other")


def hlo_type_bytes(type_str: str) -> int:
    """Output bytes of an HLO instruction's (possibly tuple) type."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        width = DTYPE_BYTES.get(dt)
        if width is None:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * width
    return total


def parse_hlo_categories(hlo_text: str) -> dict:
    """Per-category ``{"ops": count, "bytes": output_bytes}`` over
    every instruction of an optimized-HLO dump (sub-computations —
    fusion bodies, while bodies, branch computations — included; their
    instructions are what the categories exist to attribute)."""
    out: dict = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        type_str, opcode = m.groups()
        cat = hlo_category(opcode)
        slot = out.setdefault(cat, {"ops": 0, "bytes": 0})
        slot["ops"] += 1
        slot["bytes"] += hlo_type_bytes(type_str)
    return out


def parse_hlo_collectives(hlo_text: str) -> dict:
    """Per-COMMS-category ``{"ops": count, "bytes": output_bytes}``
    over the collective instructions of an optimized-HLO dump — the
    compiled-module side of the collective cross-check
    (analysis/comms.py): categories here reconcile one-to-one against
    the jaxpr walk's COLLECTIVE_PRIMS totals, and any category XLA
    *introduced* (SPMD partitioner respecification) shows up as ops
    the jaxpr side can't account for. Bytes are the instruction's
    OUTPUT type — equal to the jaxpr operand estimate on XLA:CPU
    (measured ratio 1.0, PERF.md §comms-lint); a backend typing the
    exchange per-participant would show an S-factor, which is why the
    cross-check reports the ratio instead of gating on it."""
    out: dict = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        type_str, opcode = m.groups()
        cat = HLO_COLLECTIVE_OPS.get(opcode)
        if cat is None:
            continue
        slot = out.setdefault(cat, {"ops": 0, "bytes": 0})
        slot["ops"] += 1
        slot["bytes"] += hlo_type_bytes(type_str)
    return out
