"""Kernel-lint: static analysis of the sparse-engine codegen contract.

The invariants two rounds of perf work bought — no dense ``[F, K]``
bool on the sparse path, gather-free mask construction, table-row-only
step gathers, no ``[N, 1]`` lane-padded ALU, class-local switch-branch
carries — are checkable on the TRACED program, on CPU, before any
chip run. This package is their single home:

* :mod:`.tables` — the shared primitive/HLO classification tables
  (also consumed by tests/test_codegen_shapes.py and
  stateright_tpu/wavewall.py, so the three audits cannot drift);
* :mod:`.walker` — jaxpr traversal with sub-jaxpr descent and
  source attribution;
* :mod:`.rules` — the declarative rule registry;
* :mod:`.registry` — every encoding the sparse engines are pinned
  for, with calibrated allowances;
* :mod:`.lint` — the driver (``tools/lint_kernels.py``,
  ``pytest -m lint``).
"""

from .tables import (  # noqa: F401
    ALU_PRIMS,
    CARRY_MOVE_PRIMS,
    DTYPE_BYTES,
    HLO_CATEGORY,
    HLO_WALL_CATEGORIES,
    hlo_category,
    hlo_type_bytes,
    is_gather,
    output_bytes,
    parse_hlo_categories,
)
from .walker import (  # noqa: F401
    EqnSite,
    audit_jaxpr,
    eqn_alu_n1,
    eqn_dense_bool_k,
    eqn_wide_concat_n1,
    iter_eqns,
    source_of,
)
from .rules import (  # noqa: F401
    Finding,
    RULES,
    Rule,
    TraceCtx,
    run_rules,
    run_rules_with_stats,
)
from .registry import ENCODINGS, EncodingSpec, get_encoding_spec  # noqa: F401
from .lint import (  # noqa: F401
    LINT_N,
    engine_pair_width,
    engine_pipe_params,
    format_report,
    lint_encoding,
    lint_wave_body,
    run_lint,
    trace_encoding_paths,
    trace_engine_pipeline,
    trace_wave_body_fixture,
)
