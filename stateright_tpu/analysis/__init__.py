"""Kernel-lint: static analysis of the sparse-engine codegen contract.

The invariants two rounds of perf work bought — no dense ``[F, K]``
bool on the sparse path, gather-free mask construction, table-row-only
step gathers, no ``[N, 1]`` lane-padded ALU, class-local switch-branch
carries — are checkable on the TRACED program, on CPU, before any
chip run. Round 13 adds the second rule family, COMMS-LINT: static
collective accounting and shard-safety over the sharded wave paths
(collectives only under pmax-agreed switches, all_to_all fed from the
routing seam, scalar-only reductions, no all_gather, per-wave
collective byte budgets). This package is their single home:

* :mod:`.tables` — the shared primitive/HLO classification tables
  (also consumed by tests/test_codegen_shapes.py and
  stateright_tpu/wavewall.py, so the audits cannot drift), including
  the jaxpr-collective and HLO-collective tables the comms rules and
  the ``--hlo`` cross-check classify with;
* :mod:`.walker` — jaxpr traversal with sub-jaxpr descent, source
  attribution, and the whole-jaxpr dataflow marks (shard-varying
  taint, routing-seam derivation) the comms rules share;
* :mod:`.rules` — the declarative rule registries (``RULES`` +
  ``COMMS_RULES``);
* :mod:`.registry` — every encoding the sparse engines are pinned
  for, with calibrated allowances;
* :mod:`.lint` — the codegen driver (``tools/lint_kernels.py``,
  ``pytest -m lint``);
* :mod:`.comms` — the comms driver (``tools/lint_comms.py``, the
  same ``lint`` pytest marker).
"""

from .tables import (  # noqa: F401
    ALU_PRIMS,
    CARRY_MOVE_PRIMS,
    COLLECTIVE_PRIMS,
    COMMS_BYTE_BUDGETS,
    DTYPE_BYTES,
    HLO_CATEGORY,
    HLO_COLLECTIVE_OPS,
    HLO_WALL_CATEGORIES,
    collective_bytes,
    collective_category,
    hlo_category,
    hlo_type_bytes,
    is_collective,
    is_gather,
    output_bytes,
    parse_hlo_categories,
    parse_hlo_collectives,
)
from .walker import (  # noqa: F401
    EqnSite,
    SiteWalk,
    audit_jaxpr,
    eqn_alu_n1,
    eqn_dense_bool_k,
    eqn_wide_concat_n1,
    iter_eqns,
    seam_derived_vars,
    shard_varying_vars,
    source_of,
)
from .rules import (  # noqa: F401
    COMMS_RULES,
    Finding,
    RULES,
    Rule,
    TraceCtx,
    run_rules,
    run_rules_with_stats,
)
from .registry import ENCODINGS, EncodingSpec, get_encoding_spec  # noqa: F401
from .lint import (  # noqa: F401
    LINT_N,
    engine_pair_width,
    engine_pipe_params,
    format_report,
    lint_encoding,
    lint_wave_body,
    run_lint,
    trace_encoding_paths,
    trace_engine_pipeline,
    trace_wave_body_fixture,
)
from .comms import (  # noqa: F401
    RECONCILIATION_FIXTURE,
    format_comms_report,
    hlo_collective_crosscheck,
    reconcile_collective_categories,
    run_comms_lint,
    trace_comms_fixture,
)
