"""Single-copy register on the TPU engines: the second actor-model
encoding, exercising nonempty cross-thread snapshots in the
linearizability truth table (models/single_copy_register_tpu.py).
Pinned: 2 clients / 1 server = 93 states
(examples/single-copy-register.rs:110).
"""

import numpy as np
import pytest

from stateright_tpu.models.single_copy_register import (
    SingleCopyRegisterCfg,
    single_copy_register_model,
)


def _model():
    return single_copy_register_model(SingleCopyRegisterCfg(client_count=2))


def test_single_copy_93_states_on_tpu_engine():
    host = _model().checker().spawn_bfs().join()
    tpu = (
        _model()
        .checker()
        .spawn_tpu_sortmerge(
            capacity=128, frontier_capacity=64, cand_capacity=256
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count() == 93
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()


def test_single_copy_step_exhaustive_differential():
    import jax
    import jax.numpy as jnp
    from collections import deque

    m = _model()
    enc = m.to_encoded()
    props = list(m.properties())
    step = jax.jit(enc.step_vec)
    pcond = jax.jit(enc.property_conditions_vec)
    seen = set()
    frontier = deque()
    for s in m.init_states():
        seen.add(tuple(enc.encode(s).tolist()))
        frontier.append(s)
    while frontier:
        s = frontier.popleft()
        vec = enc.encode(s)
        succs, valid = step(jnp.asarray(vec))
        succs, valid = np.asarray(succs), np.asarray(valid)
        dev = sorted(
            tuple(succs[i].tolist()) for i in range(enc.K) if valid[i]
        )
        host_next = list(m.next_states(s))
        host = sorted(tuple(enc.encode(n).tolist()) for n in host_next)
        assert dev == host, f"step divergence at {s!r}"
        pc = list(np.asarray(pcond(jnp.asarray(vec))))
        hc = [bool(p.condition(m, s)) for p in props]
        assert pc == hc, f"property divergence at {s!r}"
        for n in host_next:
            key = tuple(enc.encode(n).tolist())
            if key not in seen:
                seen.add(key)
                frontier.append(n)
    assert len(seen) == 93


def test_lin_table_snapshot_semantics():
    """Spot-check the 1296-entry truth table against hand reasoning."""
    enc = _model().to_encoded()
    t = enc._lin_table

    def idx(*triples):
        i = 0
        for ph, rv, sn in triples:
            i = i * 36 + (ph * 3 + rv) * 3 + sn
        return i

    # Both writes in flight: linearizable.
    assert t[idx((0, 0, 0), (0, 0, 0))]
    # c1 wrote A and read A; c2 still writing: fine.
    assert t[idx((3, 1, 0), (0, 0, 0))]
    # c1 read '\x00' after completing its own write: impossible.
    assert not t[idx((3, 0, 0), (0, 0, 0))]
    # c1 read B: c2's in-flight write of B may linearize first: fine.
    assert t[idx((3, 2, 0), (0, 0, 0))]
    # Both completed reads observing each other's values coherently:
    # c1 read B (c2's write), c2 read B — consistent order exists
    # (W_A, W_B, R_1=B, R_2=B).
    assert t[idx((3, 2, 0), (3, 2, 1))]
    # c1 read A with both of c2's ops (W_B, R_2=B) completed before
    # the read began: R_1 must linearize after W_B and after R_2,
    # while R_2 (which happened-after W_A) saw B — every interleaving
    # forces R_1 to observe B, so returning A is a violation.
    assert not t[idx((3, 1, 2), (3, 2, 1))]
    # But reading the default value after the peer's write completed
    # before our read began is a real-time violation.
    assert not t[idx((3, 0, 1), (1, 0, 0))]
