"""The multi-chip sharded wave engine on the virtual 8-device CPU mesh.

The sharding contract (VERDICT round-1 item 1): identical results —
unique counts, discovered-property sets, replayable counterexamples —
for shard counts 1/2/8, matching the host oracle and the reference's
pinned state counts (2pc rm=3 = 288, rm=5 = 8,832,
examples/2pc.rs:153-168).
"""

import numpy as np
import pytest

from stateright_tpu.fixtures import DGraph
from stateright_tpu.model import Property
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


@pytest.fixture(scope="module")
def host_2pc3():
    return TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()


@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_sharded_2pc_matches_host(n_shards, host_2pc3):
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded(
            n_shards=n_shards,
            capacity=1 << 10,
            frontier_capacity=128,
            cand_capacity=512,
            bucket_capacity=256
        )
        .join()
    )
    assert c.unique_state_count() == 288
    assert c.unique_state_count() == host_2pc3.unique_state_count()
    assert sorted(c.discoveries()) == sorted(host_2pc3.discoveries())
    c.assert_properties()
    # Counterexample paths replay through the host model.
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state())


@pytest.mark.slow
def test_sharded_2pc_5rms_8832():
    c = (
        TwoPhaseSys(rm_count=5)
        .checker()
        .spawn_tpu_sharded(
            n_shards=8,
            capacity=1 << 12,
            frontier_capacity=512,
            cand_capacity=2048,
            bucket_capacity=1024,
            waves_per_sync=32,
            track_paths=False,
        )
        .join()
    )
    assert c.unique_state_count() == 8832
    c.assert_properties()
    assert c.metrics["shuffle_volume"] > 0


def test_sharded_single_shard_no_shuffle():
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded(
            n_shards=1, capacity=1 << 10, frontier_capacity=128, cand_capacity=512
        )
        .join()
    )
    assert c.unique_state_count() == 288
    assert c.metrics["shuffle_volume"] == 0


def test_sharded_agrees_with_single_chip_engine():
    single = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu(
            capacity=1 << 12, frontier_capacity=512, cand_capacity=2048
        )
        .join()
    )
    sharded = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu_sharded(
            n_shards=8,
            capacity=1 << 10,
            frontier_capacity=256,
            cand_capacity=512,
            bucket_capacity=256,
        )
        .join()
    )
    assert sharded.unique_state_count() == single.unique_state_count()
    assert sharded.state_count() == single.state_count()
    assert sharded.max_depth() == single.max_depth()
    assert sorted(sharded.discoveries()) == sorted(single.discoveries())


def test_sharded_eventually_property():
    class DGraphEncoded:
        width = 1
        max_actions = 2

        def __init__(self, model):
            self.host_model = model

        def init_vecs(self):
            return np.array([[1]], dtype=np.uint32)

        def encode(self, state):
            return np.array([state], dtype=np.uint32)

        def step_vec(self, vec):
            import jax.numpy as jnp

            node = vec[0]
            s1 = jnp.where(node == 1, jnp.uint32(2), jnp.uint32(3))
            v1 = (node == 1) | (node == 2)
            s2 = jnp.uint32(4)
            v2 = node == 1
            return (
                jnp.stack([vec.at[0].set(s1), vec.at[0].set(s2)]),
                jnp.stack([v1, v2]),
            )

        def property_conditions_vec(self, vec):
            import jax.numpy as jnp

            return jnp.stack([vec[0] == 3])

        def within_boundary_vec(self, vec):
            return True

    model = (
        DGraph.with_path([1, 2, 3])
        .path([1, 4])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    checker = (
        model.checker()
        .spawn_tpu_sharded(
            encoded=DGraphEncoded(model),
            n_shards=4,
            capacity=64,
            frontier_capacity=8,
        )
        .join()
    )
    path = checker.assert_any_discovery("reaches 3")
    assert path.states() == [1, 4]


def test_sharded_target_max_depth():
    single = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .target_max_depth(5)
        .spawn_tpu(capacity=1 << 10)
        .join()
    )
    sharded = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .target_max_depth(5)
        .spawn_tpu_sharded(
            n_shards=4,
            capacity=1 << 10,
            frontier_capacity=128,
            cand_capacity=512,
            bucket_capacity=256
        )
        .join()
    )
    assert sharded.unique_state_count() == single.unique_state_count()
    assert sharded.max_depth() == 5


def test_sharded_fast_mode_discovery_fingerprints():
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded(
            n_shards=2,
            capacity=1 << 10,
            frontier_capacity=128,
            cand_capacity=512,
            bucket_capacity=256,
            track_paths=False,
        )
        .join()
    )
    assert c.unique_state_count() == 288
    names = c.discovered_property_names()
    assert names == {"abort agreement", "commit agreement"}
    with pytest.raises(RuntimeError):
        c.discoveries()


@pytest.mark.parametrize("shards", [1, 2, 8])
def test_sharded_sortmerge_matches_host(shards):
    """The sharded SORT-MERGE engine (VERDICT r2 #4): owner-local dedup
    on the sorted-array fast path, state-identical across shard counts,
    WITH path tracking — discovery paths replay through the host model."""
    import jax

    devices = jax.devices()
    if len(devices) < shards:
        pytest.skip(f"need {shards} devices")
    from jax.sharding import Mesh

    import numpy as np

    mesh = Mesh(np.array(devices[:shards]), ("shard",))
    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded_sortmerge(
            mesh=mesh,
            capacity=512,
            frontier_capacity=128,
            cand_capacity=1024,
            bucket_capacity=512,
        )
        .join()
    )
    assert c.unique_state_count() == host.unique_state_count() == 288
    assert sorted(c.discoveries()) == sorted(host.discoveries())
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state())


def test_sharded_sparse_paxos_with_paths():
    """Sparse action dispatch through the SHARDED engine (round 4):
    the pair pipeline runs shard-locally and only real candidates
    enter the routing sort and the all_to_all. Counts, property set,
    and replayed paths match the host across shard counts, and the
    class ladders engage (f_min below the frontier capacity)."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    model = paxos_model(PaxosModelCfg(client_count=1, server_count=3))
    host = model.checker().spawn_bfs().join()
    for shards in (1, 2):
        ck = (
            paxos_model(PaxosModelCfg(client_count=1, server_count=3))
            .checker()
            .spawn_tpu_sharded_sortmerge(
                n_shards=shards,
                capacity=1 << 10,
                frontier_capacity=1 << 7,
                cand_capacity=1 << 9,
                pair_width=16,
                f_min=32,       # exercise the frontier ladder
                v_min=128,      # exercise the visited ladder
                ladder_step=2,
                v_ladder_step=4,
            )
            .join()
        )
        assert ck.unique_state_count() == 265
        assert sorted(ck.discoveries()) == sorted(host.discoveries())
        p = ck.discovery("value chosen")
        assert p is not None and len(p.actions()) >= 1


def test_sharded_sparse_chunked_mode_matches():
    """The sharded memory-lean chunked sparse path (successors
    fingerprinted in chunks, routed tiles recomputed in dest_tile) —
    forced via a tiny flat budget — matches the host with replayable
    paths across 2 shards."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    model = paxos_model(PaxosModelCfg(client_count=1, server_count=3))
    host = model.checker().spawn_bfs().join()
    ck = (
        paxos_model(PaxosModelCfg(client_count=1, server_count=3))
        .checker()
        .spawn_tpu_sharded_sortmerge(
            n_shards=2,
            capacity=1 << 10,
            frontier_capacity=1 << 7,
            cand_capacity=1 << 9,
            pair_width=16,
            flat_budget_bytes=1 << 10,
        )
        .join()
    )
    assert ck.unique_state_count() == 265
    assert sorted(ck.discoveries()) == sorted(host.discoveries())
    p = ck.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1
