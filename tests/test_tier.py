"""Tiered-visited-set gate (``tier`` marker, stateright_tpu/tier.py).

The exactness contract: a hot tier forced tiny (so the engines spill
repeatedly to host-DRAM cold runs and run the deferred-commit tiered
chunk loop for most of the search) reproduces the pinned counts
EXACTLY — paxos 2c/3s = 16,668 with a replayable counterexample path,
2pc rm=7 = 296,448 — with traced runs showing ZERO per-wave counter
divergence against the all-resident baseline. Plus: the ColdStore
primitives (membership, run disjointness, owner repartition), the
``tier_spill`` event schema and trace_diff alignment (tiered pairs
compare, resident baselines skip), checkpoint kill/resume across a
spill boundary, the 2→4 elastic re-shard with cold runs present, the
un-tier resume, the memplan hot/cold split policy, and the
``--checkpoint-every=auto`` cadence math.
"""

import numpy as np
import pytest

from stateright_tpu import faultinject
from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

pytestmark = pytest.mark.tier


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faultinject.disarm_all()


def _twopc3(**kw):
    kw.setdefault("tier_hot_rows", 32)
    return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=1 << 10, frontier_capacity=128, cand_capacity=512,
        waves_per_sync=2, **kw,
    )


def _twopc4(**kw):
    return TwoPhaseSys(rm_count=4).checker().spawn_tpu_sortmerge(
        capacity=1 << 11, frontier_capacity=512, cand_capacity=4096,
        waves_per_sync=4, **kw,
    )


def _mesh2pc4(n_shards, **kw):
    kw.setdefault("cand_capacity", 4096)
    kw.setdefault("bucket_capacity", 2048)
    return TwoPhaseSys(rm_count=4).checker().spawn_tpu_sharded_sortmerge(
        n_shards=n_shards, capacity=1 << 11, frontier_capacity=256,
        waves_per_sync=4, **kw,
    )


# -- the ColdStore primitives ---------------------------------------------


def test_cold_store_membership_runs_and_repartition():
    from stateright_tpu.tier import ColdStore, member_mask, pack_u64

    rng = np.random.default_rng(7)

    def sorted_pair(n):
        lo = rng.integers(0, 1 << 32, n, dtype=np.uint32)
        hi = rng.integers(0, 1 << 32, n, dtype=np.uint32)
        order = np.lexsort((lo, hi))
        return lo[order], hi[order]

    lo, hi = sorted_pair(500)
    q_lo, q_hi = sorted_pair(200)
    run = pack_u64(lo, hi)
    got = member_mask(np.sort(run), pack_u64(q_lo, q_hi))
    want = np.isin(pack_u64(q_lo, q_hi), run)
    assert (got == want).all()

    # multi-run ingest (sync mode), compaction past max_runs, and the
    # hot+cold accounting invariant
    store = ColdStore(n_shards=1, max_runs=2)
    total = 0
    for _ in range(5):
        lo, hi = sorted_pair(100)
        store.ingest([(lo, hi)], asynchronous=False)
        total += 100
    assert store.rows() == total  # random u64s: no collisions
    assert store.run_count() <= 2  # compaction bounded the fan-in
    assert store.bytes() == total * 8
    assert store.member(0, lo, hi).all()

    # owner repartition: filtering preserves sort, owners route by
    # lo % S (the (owner, fp) seam)
    re4 = store.repartitioned(4)
    assert re4.rows() == store.rows()
    for d in range(4):
        for run in re4.runs[d]:
            assert (np.diff(run.astype(np.uint64)) > 0).all()
            owners = (run & np.uint64(0xFFFFFFFF)) % np.uint64(4)
            assert (owners == d).all()

    # snapshot round-trip
    rebuilt = ColdStore.from_runs(store.snapshot_runs(),
                                  spills=store.spills)
    assert rebuilt.rows() == store.rows()
    assert rebuilt.member(0, lo, hi).all()


def test_decide_hot_rows_policy():
    from stateright_tpu.memplan import decide_hot_rows

    # the whole ladder fits: tier dormant (ceiling = capacity)
    assert decide_hot_rows(1 << 20, 1 << 10, 2, 1 << 8,
                           1 << 40) == 1 << 20
    # nothing past the bottom fits: ceiling = v_min
    assert decide_hot_rows(1 << 20, 1 << 10, 2, 1 << 8, 1) == 1 << 10
    # the budget prices (V + F) * 8 * 2 (vkeys + merge scratch):
    # pick the largest class under it
    F = 1 << 8
    budget = 2 * ((1 << 14) + F) * 8
    hot = decide_hot_rows(1 << 20, 1 << 10, 2, F, budget)
    assert hot == 1 << 14
    assert decide_hot_rows(1 << 20, 1 << 10, 2, F,
                           budget - 1) == 1 << 13


def test_auto_checkpoint_cadence_policy():
    from stateright_tpu.checkpoint import auto_cadence

    # 0.5s snapshot vs 10s chunks: every chunk already <=5%
    assert auto_cadence(0.5, 10.0) == 1
    # 0.5s snapshot vs 1s chunks: need 10 chunks per snapshot
    assert auto_cadence(0.5, 1.0) == 10
    # exact boundary: ceil keeps overhead AT the target
    assert auto_cadence(1.0, 4.0, target=0.05) == 5
    # clamps
    assert auto_cadence(100.0, 0.001) == 256
    assert auto_cadence(0.0, 1.0) == 1  # unmeasured snapshot wall
    assert auto_cadence(1.0, 0.0) == 256  # unmeasured chunk wall
    # custom target
    assert auto_cadence(1.0, 1.0, target=0.5) == 2


def test_auto_cadence_engine_integration(tmp_path):
    """``checkpoint_every="auto"`` writes snapshots and re-derives
    its cadence from the measured walls (no crash, snapshot exists,
    cadence is a positive int)."""
    snap = str(tmp_path / "auto.ckpt")
    c = _twopc3(tier_hot_rows=None, checkpoint_every="auto",
                checkpoint_path=snap)
    c.join()
    assert c.unique_state_count() == 288
    import os

    assert os.path.exists(snap)
    assert c._ckpt_auto_every >= 1


# -- forced-spill count parity (the pinned counts) ------------------------


def test_tier_2pc_rm3_forced_spill_288():
    c = _twopc3().join()
    assert c.unique_state_count() == 288
    assert c.metrics["tier_spills"] >= 2  # spilled repeatedly
    # the two tiers partition the visited set exactly
    assert c.metrics["cold_rows"] + c.metrics["hot_rows"] == 288
    # cold holds the majority at this forced ceiling
    assert c.metrics["cold_rows"] > 288 // 2
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state()), name


def test_tier_paxos_2c3s_forced_spill_16668():
    """paxos 2c/3s with the hot tier capped at 1/16th of the space
    spills repeatedly and still reproduces the pinned 16,668 with a
    replayable counterexample path."""
    c = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 15, frontier_capacity=1 << 12,
            cand_capacity=1 << 14, waves_per_sync=8,
            tier_hot_rows=1024,
        )
    )
    c.join()
    assert c.unique_state_count() == 16668
    assert c.metrics["tier_spills"] >= 2
    assert c.metrics["cold_rows"] > 16668 // 2
    assert sorted(c.discoveries()) == ["value chosen"]
    path = c.discovery("value chosen")
    prop = c.model.property_by_name("value chosen")
    assert prop.condition(c.model, path.last_state())


def test_tier_2pc_rm7_forced_spill_296448():
    """The largest CPU-feasible lane: 2pc rm=7 with the hot tier at
    1/8th of the space reproduces the pinned 296,448. The frontier
    gets one notch of headroom over the resident config: in tiered
    mode the bound applies to PROVISIONAL winners (hot-new rows
    before the cold membership pass), which exceed the truly-new
    peak once most of the visited set is cold."""
    c = TwoPhaseSys(rm_count=7).checker().spawn_tpu_sortmerge(
        capacity=1 << 19, frontier_capacity=1 << 17,
        cand_capacity=1 << 19, track_paths=False,
        waves_per_sync=4, tier_hot_rows=1 << 16,
    )
    c.join()
    assert c.unique_state_count() == 296448
    assert c.metrics["tier_spills"] >= 2
    assert c.metrics["cold_rows"] > 296448 // 2
    c.assert_properties()


# -- traced exactness: zero counter divergence vs resident ----------------


def test_tier_traced_zero_divergence_and_schema():
    """A traced forced-spill run diffs against the traced all-resident
    baseline with ZERO wave-counter divergence — the per-wave proof
    that the deferred-commit protocol retires false-new rows before
    any count commits. Also pins the tier_spill schema, the watermark
    cold_tier_bytes lane, and the tier trace_diff block (tiered pair
    compares; resident baseline skips)."""
    from stateright_tpu.telemetry import (
        RunTracer,
        diff_traces,
        memory_summary,
        validate_events,
    )

    ta = RunTracer()
    with ta.activate():
        a = _twopc4().join()
    tb = RunTracer()
    with tb.activate():
        b = _twopc4(tier_hot_rows=64).join()
    assert a.unique_state_count() == b.unique_state_count() == 1568
    validate_events(ta.events)
    validate_events(tb.events)

    spills = [e for e in tb.events if e["ev"] == "tier_spill"]
    assert len(spills) >= 2
    last = spills[-1]
    assert last["cold_rows_total"] * 8 == last["cold_bytes_total"]
    assert last["spill_index"] == len(spills)

    wm = [e for e in tb.events if e["ev"] == "memory_watermark"][-1]
    assert wm["cold_tier_bytes"] == last["cold_bytes_total"]
    tier_hr = wm["headroom"]["tier"]
    assert tier_hr["cold_rows_total"] == last["cold_rows_total"]
    # the resident baseline's watermark carries the lane as null
    wm_a = [e for e in ta.events if e["ev"] == "memory_watermark"][-1]
    assert wm_a["cold_tier_bytes"] is None

    # resident vs tiered: counters must match, tier block skips
    rep = diff_traces(ta.events, tb.events)
    assert rep["divergences"] == []
    assert rep["tier"]["skipped"] is True

    # tiered vs tiered: tier counters compare exactly
    tc = RunTracer()
    with tc.activate():
        c = _twopc4(tier_hot_rows=64).join()
    assert c.unique_state_count() == 1568
    rep2 = diff_traces(tb.events, tc.events)
    assert rep2["divergences"] == []
    assert rep2["tier"]["divergences"] == []
    assert rep2["tier"]["skipped"] is False
    assert "tier_spill_wall_sec" in rep2["tier"]["lanes"]

    # a doctored cold total is a DIVERGENCE, not a timing delta
    import copy

    bad = copy.deepcopy(tc.events)
    for ev in bad:
        if ev["ev"] == "tier_spill":
            ev["cold_rows_total"] += 1
    rep3 = diff_traces(tb.events, bad)
    assert any(d["field"] == "tier_cold_rows_final"
               for d in rep3["tier"]["divergences"])
    assert not rep3["ok"]

    # mem_report renders the tiered run and prints the split
    summary = memory_summary(tb.events)
    assert summary["tier_spills"]
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "mem_report_mod",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "mem_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.format_report(summary)
    assert "tiered visited set" in report
    assert "tier spills" in report


# -- durability across the tier ------------------------------------------


def _kill_at(spawn, snap, chunk, **kw):
    c = spawn(checkpoint_every=1, checkpoint_path=snap, **kw)
    c.max_fault_retries = 0
    faultinject.arm("raise", "chunk_boundary", chunk)
    with pytest.raises(faultinject.InjectedFault):
        c.join()
    faultinject.disarm_all()
    return c


def test_tier_kill_resume_across_spill_boundary(tmp_path):
    """Kill a tiered run at chunk boundaries spanning the first spill
    and deep into the tiered phase; resume reproduces the pinned 288
    with replayable paths (the snapshot carries the cold runs AND the
    host-drained parent-log rows)."""
    base = _twopc3().join()
    n_chunks = base.latency_accounting()["chunks"]
    assert n_chunks >= 4
    for k in (0, 1, n_chunks // 2, n_chunks - 2):
        snap = str(tmp_path / f"t{k}.ckpt")
        _kill_at(_twopc3, snap, k)
        from stateright_tpu.checkpoint import load_snapshot

        manifest, _ = load_snapshot(snap)
        r = _twopc3()
        r.resume_from(snap)
        r.join()
        assert r.unique_state_count() == 288, f"boundary {k}"
        for name, path in r.discoveries().items():
            prop = r.model.property_by_name(name)
            assert prop.condition(r.model, path.last_state()), name


def test_tier_untier_resume(tmp_path):
    """A tiered snapshot resumes into a RESIDENT checker when the
    target capacity holds both tiers: the cold runs merge back into
    the visited prefix and the host-drained parent log re-homes —
    same count, replayable paths. A resident target too small for
    the folded set refuses loudly."""
    snap = str(tmp_path / "untier.ckpt")
    base = _twopc3().join()
    n_chunks = base.latency_accounting()["chunks"]
    _kill_at(_twopc3, snap, n_chunks - 2)
    r = _twopc3(tier_hot_rows=None)  # tier OFF: fold to resident
    r.resume_from(snap)
    r.join()
    assert r.unique_state_count() == 288
    assert r.metrics.get("tier_spills") is None  # stayed resident
    for name, path in r.discoveries().items():
        prop = r.model.property_by_name(name)
        assert prop.condition(r.model, path.last_state()), name

    from stateright_tpu.checkpoint import SnapshotIncompatibleError

    # a resident target too small for the folded set refuses loudly
    # BEFORE any device work (either at the hot re-shard slice or at
    # the un-tier fold, whichever trips first)
    tiny = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=64, frontier_capacity=32, cand_capacity=128,
        waves_per_sync=2,
    )
    with pytest.raises(SnapshotIncompatibleError):
        tiny.resume_from(snap)


@pytest.fixture(scope="module")
def host_2pc4():
    return TwoPhaseSys(rm_count=4).checker().spawn_bfs().join()


def test_tier_mesh_and_reshard_with_cold_runs(tmp_path, host_2pc4):
    """The sharded tier on the virtual mesh: a forced-spill S=2 run
    matches the host oracle; killed mid-tier it resumes same-shard
    AND through the 2→4 (owner, fp) re-shard WITH cold runs present
    (each run splits by the new owner), to the same count with
    replayable paths."""
    expected = host_2pc4.unique_state_count()
    c = _mesh2pc4(2, tier_hot_rows=64).join()
    assert c.unique_state_count() == expected
    assert c.metrics["tier_spills"] >= 2
    assert c.metrics["cold_rows"] > expected // 2

    snap = str(tmp_path / "mesh.ckpt")
    _kill_at(lambda **kw: _mesh2pc4(2, tier_hot_rows=64, **kw),
             snap, 8)
    from stateright_tpu.checkpoint import load_snapshot

    manifest, _ = load_snapshot(snap)
    assert manifest["tier"]["cold_rows_total"] > 0  # mid-tier kill

    same = _mesh2pc4(2, tier_hot_rows=64)
    same.resume_from(snap)
    same.join()
    assert same.unique_state_count() == expected

    re4 = _mesh2pc4(4, tier_hot_rows=64)
    m = re4.resume_from(snap)
    assert m["n_shards"] == 2
    re4.join()
    assert re4.unique_state_count() == expected
    assert sorted(re4.discoveries()) == sorted(host_2pc4.discoveries())
    for name, path in re4.discoveries().items():
        prop = re4.model.property_by_name(name)
        assert prop.condition(re4.model, path.last_state()), name


def test_tier_auto_ceiling_dormant():
    """``tier_hot_rows="auto"`` with a budget holding the whole
    ladder leaves the tier dormant (no spills, all-resident run);
    with a budget that only fits a small ladder class it activates.
    (The ladder must reach below the capacity for a split to exist:
    v_min < capacity.)"""
    c = _twopc3(tier_hot_rows="auto")  # default budget >> 2pc rm=3
    c.join()
    assert c.unique_state_count() == 288
    assert c.metrics.get("tier_spills") is None

    # budget = exactly one 64-row class's vkeys + merge scratch:
    # decide_hot_rows picks 64, the run spills past it
    budget = 2 * (64 + 128) * 8
    c2 = _twopc3(tier_hot_rows="auto", tier_budget_bytes=budget,
                 v_min=64)
    c2.join()
    assert c2.unique_state_count() == 288
    assert c2._tier_hot_ceiling == 64
    assert c2.metrics["tier_spills"] >= 1


# -- tiered retention: warm-start for forced-spill runs -------------------


def test_tiered_retention_warm_start_zero_new_waves(tmp_path):
    """``retain_final_snapshot`` no longer refuses tiered sessions:
    the final carry serializes with BOTH tiers (hot carry + cold runs
    + the host parent-log segment), and a fresh checker resuming from
    the retained snapshot settles at its first sync with ZERO new
    waves dispatched at the pinned count — the forced-spill analogue
    of the resident warm-start re-check."""
    import os

    from stateright_tpu import checkpoint
    from stateright_tpu.telemetry import RunTracer

    def build():
        # frontier gets the tiered headroom notch (cand 4096) so the
        # forced-spill run cannot f_overflow mid-run
        return TwoPhaseSys(rm_count=4).checker().spawn_tpu_sortmerge(
            capacity=1 << 11, frontier_capacity=4096,
            cand_capacity=4096, waves_per_sync=4, tier_hot_rows=256,
        )

    cold = build()
    cold.keep_final_carry = True
    cold.join()
    assert cold.unique_state_count() == 1568
    assert cold.metrics["tier_spills"] >= 2  # the refusal's old trigger

    path = os.path.join(str(tmp_path), "tiered.ckpt")
    manifest = checkpoint.retain_final_snapshot(cold, path)
    assert manifest is not None
    tier = manifest["tier"]
    assert tier["spills"] == cold.metrics["tier_spills"]
    assert tier["cold_rows_total"] == cold.metrics["cold_rows"]
    assert tier["plog_host_rows"] > 0  # paths survive the spill

    warm = build()
    tracer = RunTracer()
    with tracer.activate():
        warm.resume_from(path)
        warm.join()
    assert warm.unique_state_count() == 1568
    assert warm._total_states == cold._total_states
    # zero NEW waves: the retained carry is already done — the warm
    # run settles at its first sync
    assert [e for e in tracer.events if e["ev"] == "wave"] == []
    for name, p in warm.discoveries().items():
        prop = warm.model.property_by_name(name)
        assert prop.condition(warm.model, p.last_state()), name
