"""Checkpoint/resume + fault-injection gate (``ckpt`` marker).

The durability contract (stateright_tpu/checkpoint.py +
faultinject.py): kill-and-resume COUNT PARITY — paxos 2c/3s killed at
every chunk boundary (and once mid-chunk via an injected fault under
supervision) resumes to the exact pinned 16,668; 2pc rm=7 kill/resume
reproduces the pinned 296,448; the 2pc rm=4 virtual mesh killed at
every boundary resumes both same-shard and through the 2→4
(owner, fp) re-shard to the host oracle's 1,568 — plus the
refuse-loudly cells (torn snapshot, stale manifest, incompatible
target), the supervised-retry/overflow boundary, the hardened
auto-budget store, the hybrid racer's clean loser cancellation on
resume, and the resumed-trace report/diff degradations.
"""

import json
import os
import subprocess
import sys
import threading
import time
import warnings

import pytest

from stateright_tpu import faultinject
from stateright_tpu.checkpoint import (
    SnapshotCorruptError,
    SnapshotIncompatibleError,
    SnapshotStaleError,
    load_snapshot,
)
from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

pytestmark = pytest.mark.ckpt

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faultinject.disarm_all()


def _twopc3(**kw):
    return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=1 << 10, frontier_capacity=128, cand_capacity=512,
        waves_per_sync=2, **kw,
    )


def _paxos2(**kw):
    return (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 15, frontier_capacity=1 << 12,
            cand_capacity=1 << 14, waves_per_sync=8, **kw,
        )
    )


def _mesh2pc4(n_shards, **kw):
    kw.setdefault("cand_capacity", 4096)
    kw.setdefault("bucket_capacity", 2048)
    return TwoPhaseSys(rm_count=4).checker().spawn_tpu_sharded_sortmerge(
        n_shards=n_shards, capacity=1 << 11, frontier_capacity=256,
        waves_per_sync=4, **kw,
    )


def _kill_at(spawn, snap, chunk, **kw):
    """Run ``spawn(...)`` with per-chunk checkpointing and an injected
    chunk-boundary fault (retries off so the raise escapes): the
    in-process model of a kill — the run dies at the boundary, the
    snapshot written just before survives."""
    c = spawn(checkpoint_every=1, checkpoint_path=snap, **kw)
    c.max_fault_retries = 0
    faultinject.arm("raise", "chunk_boundary", chunk)
    with pytest.raises(faultinject.InjectedFault):
        c.join()
    faultinject.disarm_all()
    assert os.path.exists(snap)
    return c


# -- snapshot format ------------------------------------------------------


def test_snapshot_manifest_and_checksums(tmp_path):
    snap = str(tmp_path / "t.ckpt")
    _kill_at(_twopc3, snap, 1)
    manifest, buffers = load_snapshot(snap)
    assert manifest["version"] == 1
    assert manifest["family"] == "sortmerge"
    assert manifest["kind"] == "single"
    assert manifest["n_shards"] == 1
    assert manifest["track_paths"] is True
    assert manifest["wave"] > 0 and manifest["unique"] > 0
    # the declared buffer set IS the chunk carry the memory ledger
    # names: visited keys, frontier, ebits, parent log, counters,
    # cumulative discovery lanes
    for leaf in ("vkeys", "plog", "pl_n", "frontier", "fval",
                 "ebits", "n_frontier", "depth", "waves", "gen_lo",
                 "gen_hi", "new", "disc_found", "disc_lo",
                 "disc_hi"):
        assert leaf in buffers, leaf
        assert leaf in manifest["buffers"]
    assert manifest["snapshot_bytes"] == sum(
        b.nbytes for b in buffers.values()
    )
    # auto-budget state rides the manifest (the resume-side budget)
    assert "cand_capacity" in manifest["budget"]


# -- kill-and-resume count parity (pinned counts) -------------------------


def test_paxos_2c3s_killed_at_every_chunk_boundary(tmp_path):
    """paxos 2c/3s killed at EVERY chunk boundary resumes to the
    exact pinned 16,668 with the host discovery set and a replayable
    path (the parent log survives the snapshot)."""
    baseline = _paxos2().join()
    assert baseline.unique_state_count() == 16668
    n_chunks = baseline.latency_accounting()["chunks"]
    assert n_chunks >= 2  # several boundaries to kill at
    for k in range(n_chunks):
        snap = str(tmp_path / f"px_{k}.ckpt")
        _kill_at(_paxos2, snap, k)
        r = _paxos2()
        r.resume_from(snap)
        r.join()
        assert r.unique_state_count() == 16668, f"boundary {k}"
        assert sorted(r.discoveries()) == ["value chosen"], k
        path = r.discovery("value chosen")
        prop = r.model.property_by_name("value chosen")
        assert prop.condition(r.model, path.last_state())


def test_paxos_midchunk_fault_supervised_recovery(tmp_path):
    """A mid-chunk device fault under supervision self-recovers from
    the last snapshot in ONE join — bounded backoff, identical final
    count — instead of dying."""
    snap = str(tmp_path / "px_mid.ckpt")
    c = _paxos2(checkpoint_every=1, checkpoint_path=snap)
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "mid_chunk", 1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c.join()
    assert c.unique_state_count() == 16668
    assert any("supervised recovery" in str(x.message) for x in w)


def test_2pc_rm7_kill_resume_296448(tmp_path):
    """The largest CPU-feasible lane: 2pc rm=7 killed at a chunk
    boundary and resumed reproduces the pinned 296,448 exactly."""
    def spawn(**kw):
        return TwoPhaseSys(rm_count=7).checker().spawn_tpu_sortmerge(
            capacity=1 << 19, frontier_capacity=1 << 16,
            cand_capacity=1 << 19, track_paths=False,
            waves_per_sync=4, **kw,
        )

    snap = str(tmp_path / "rm7.ckpt")
    _kill_at(spawn, snap, 2)
    r = spawn()
    r.resume_from(snap)
    r.join()
    assert r.unique_state_count() == 296448
    r.assert_properties()


@pytest.fixture(scope="module")
def host_2pc4():
    return TwoPhaseSys(rm_count=4).checker().spawn_bfs().join()


def test_mesh_2pc4_every_boundary_same_shard_and_2_to_4(
        tmp_path, host_2pc4):
    """The elastic re-shard proof at tier-1 scale: 2pc rm=4 on the
    virtual S=2 mesh killed at every chunk boundary resumes to the
    host oracle's exact count — SAME-shard by direct upload, and at
    S=4 through the (owner, fp) re-route. Shard count is a
    resume-time choice."""
    expected = host_2pc4.unique_state_count()
    baseline = _mesh2pc4(2).join()
    assert baseline.unique_state_count() == expected
    n_chunks = baseline.latency_accounting()["chunks"]
    assert n_chunks >= 2
    for k in range(n_chunks):
        snap = str(tmp_path / f"mesh_{k}.ckpt")
        _kill_at(lambda **kw: _mesh2pc4(2, **kw), snap, k)
        # same-shard direct upload
        same = _mesh2pc4(2)
        same.resume_from(snap)
        same.join()
        assert same.unique_state_count() == expected, f"S=2 at {k}"
        # 2 -> 4 elastic re-shard
        re4 = _mesh2pc4(4)
        manifest = re4.resume_from(snap)
        assert manifest["n_shards"] == 2
        re4.join()
        assert re4.unique_state_count() == expected, f"S=4 at {k}"
        assert sorted(re4.discoveries()) == sorted(
            host_2pc4.discoveries()
        )
    # discovery paths replay through the host model after a re-shard
    for name, path in re4.discoveries().items():
        prop = re4.model.property_by_name(name)
        assert prop.condition(re4.model, path.last_state())


# -- refuse-loudly cells --------------------------------------------------


@pytest.fixture()
def twopc3_snapshot(tmp_path):
    snap = str(tmp_path / "cell.ckpt")
    _kill_at(_twopc3, snap, 1)
    return snap


def test_torn_snapshot_refused(tmp_path, twopc3_snapshot):
    import shutil

    for mode in ("truncate", "flip"):
        bad = str(tmp_path / f"bad_{mode}.ckpt")
        shutil.copy(twopc3_snapshot, bad)
        faultinject.corrupt_snapshot(bad, mode)
        with pytest.raises(SnapshotCorruptError):
            _twopc3().resume_from(bad)


def test_stale_manifest_refused(tmp_path, twopc3_snapshot):
    import shutil

    for field in ("git_sha", "encoding"):
        bad = str(tmp_path / f"stale_{field}.ckpt")
        shutil.copy(twopc3_snapshot, bad)
        faultinject.stale_manifest(bad, field)
        with pytest.raises(SnapshotStaleError):
            _twopc3().resume_from(bad)
    # a DIFFERENT model's checker is stale by encoding fingerprint
    with pytest.raises(SnapshotStaleError):
        _paxos2().resume_from(twopc3_snapshot)


def test_incompatible_targets_refused(twopc3_snapshot):
    # cross-family: the hash engine can't interpret a sorted prefix
    with pytest.raises(SnapshotIncompatibleError):
        TwoPhaseSys(rm_count=3).checker().spawn_tpu(
            capacity=1 << 10, frontier_capacity=128, waves_per_sync=2,
        ).resume_from(twopc3_snapshot)
    # track_paths flip: the parent log exists on one side only
    with pytest.raises(SnapshotIncompatibleError):
        _twopc3(track_paths=False).resume_from(twopc3_snapshot)
    # a re-shard target too small for the carried state refuses
    # loudly BEFORE any device work
    tiny = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=64, frontier_capacity=32, cand_capacity=128,
        waves_per_sync=2,
    )
    with pytest.raises(SnapshotIncompatibleError, match="capacity"):
        tiny.resume_from(twopc3_snapshot)


def test_hash_reshard_directions(tmp_path):
    """The degrade-and-continue round lifted PR 11's refuse-by-name
    for the sharded-hash -> sharded-hash case: the per-shard tables
    rebuild host-side by re-insertion through the (owner, fp) route.
    Single-chip ⇄ sharded hash keeps refusing — with a message that
    names the supported direction."""
    snap = str(tmp_path / "hash.ckpt")

    def spawn(n, **kw):
        return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sharded(
            n_shards=n, capacity=1 << 10, frontier_capacity=128,
            cand_capacity=512, bucket_capacity=256, waves_per_sync=2,
            **kw,
        )

    _kill_at(lambda **kw: spawn(2, **kw), snap, 1)
    # same-config hash resume works (direct upload)...
    r = spawn(2)
    r.resume_from(snap)
    r.join()
    assert r.unique_state_count() == 288
    # ...and the sharded -> sharded re-shard now works too: 2 -> 4
    # by host-side re-insertion, exact count + discoveries
    re4 = spawn(4)
    manifest = re4.resume_from(snap)
    assert manifest["n_shards"] == 2
    re4.join()
    assert re4.unique_state_count() == 288
    assert sorted(re4.discoveries()) == sorted(r.discoveries())
    # single-chip ⇄ sharded hash keeps refusing BY NAME, and the
    # message says which direction IS supported
    single = TwoPhaseSys(rm_count=3).checker().spawn_tpu(
        capacity=1 << 10, frontier_capacity=128, waves_per_sync=2,
    )
    with pytest.raises(SnapshotIncompatibleError,
                       match="sharded-hash -> sharded-hash"):
        single.resume_from(snap)


def test_engine_overflow_is_not_supervised(tmp_path):
    """Engine overflow errors (plain RuntimeErrors with sizing
    advice) raise straight through the supervisor — the auto-budget
    retry owns those, and retrying them from a snapshot would loop."""
    c = TwoPhaseSys(rm_count=4).checker().spawn_tpu_sortmerge(
        capacity=1 << 11, frontier_capacity=256, cand_capacity=64,
        waves_per_sync=2, checkpoint_every=1,
        checkpoint_path=str(tmp_path / "ovf.ckpt"),
    )
    c.retry_backoff_sec = 0.01
    with pytest.raises(RuntimeError, match="overflow"):
        c.join()


# -- satellite: hardened auto-budget store --------------------------------


def test_corrupt_budget_store_falls_back_with_warning(
        tmp_path, monkeypatch):
    """A truncated/corrupt budget store (crash mid-write from a
    pre-atomic version, disk truncation) must fall back to defaults
    with a one-line warning instead of raising at engine start."""
    from stateright_tpu.checkers.tpu_sortmerge import (
        SortMergeTpuBfsChecker,
    )

    store = str(tmp_path / "budgets.json")
    with open(store, "w") as fh:
        fh.write('{"some/key": {"cand_capacity": 123')  # torn JSON
    monkeypatch.setattr(
        SortMergeTpuBfsChecker, "_budget_store", lambda self: store
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=128,
            cand_capacity="auto", waves_per_sync=2,
        )
    assert any("auto-budget store" in str(x.message)
               and "corrupt" in str(x.message) for x in w)
    assert c.cand_capacity  # the growth heuristic filled in
    c.join()
    assert c.unique_state_count() == 288
    # the clean run rewrote the store atomically: it parses again
    with open(store) as fh:
        assert json.load(fh)


# -- satellite: hybrid racer's loser cancelled cleanly on resume ----------


class _SlowHostTwoPhase(TwoPhaseSys):
    """Host enumeration slowed so the device side wins the race
    deterministically (the device engine never calls actions() during
    the search — only path replay does)."""

    def actions(self, state):
        time.sleep(0.002)
        return super().actions(state)


def test_hybrid_resume_cancels_loser_cleanly(tmp_path):
    """A resumed hybrid race must not leave a stale host thread
    emitting events into the new trace run: the loser is cancelled
    AND joined on every exit path, its run stays CANCELLED (no
    exhaustion verdicts — the PR-10 pin), and no thread outlives
    join()."""
    from stateright_tpu.telemetry import RunTracer, validate_events

    snap = str(tmp_path / "hy.ckpt")
    _kill_at(_twopc3, snap, 1)

    dev_kw = dict(capacity=1 << 10, frontier_capacity=128,
                  cand_capacity=512, waves_per_sync=2)
    before = threading.active_count()
    tracer = RunTracer()
    with tracer.activate():
        hy = _SlowHostTwoPhase(rm_count=3).checker().spawn_hybrid(
            **dev_kw
        )
        hy.resume_from(snap)
        hy.join()
    assert hy.winner == "device"
    assert hy.unique_state_count() == 288
    assert threading.active_count() == before  # loser joined
    validate_events(tracer.events)
    # the device run restored from the snapshot
    assert any(e["ev"] == "restore" for e in tracer.events)
    # the host loser's run emitted NO exhaustion verdicts (a
    # cancelled partial search settled nothing) and NO events after
    # the tracer deactivated (the thread is gone, not stale)
    host_runs = {
        e["run"] for e in tracer.events
        if e["ev"] == "run_begin"
        and e["lane"].get("engine") == "DfsChecker"
    }
    assert host_runs  # the race really ran a host side
    assert not [
        e for e in tracer.events
        if e["ev"] == "verdict" and e["run"] in host_runs
    ]
    n_events = len(tracer.events)
    time.sleep(0.05)
    assert len(tracer.events) == n_events


# -- satellite: resumed traces through diff + reports ---------------------


def _traced(fn):
    from stateright_tpu.telemetry import RunTracer

    tr = RunTracer()
    with tr.activate():
        c = fn()
    return tr, c


def test_resumed_trace_diff_and_reports(tmp_path):
    """End-to-end on a traced kill/resume pair: validate_events
    accepts the new event types, trace_diff aligns the resumed wave
    stream with the uninterrupted baseline at ZERO counter
    divergence, and mem_report/latency_report render a wave>0 run
    without crashing or misattributing time-to-first-wave."""
    from stateright_tpu.telemetry import (
        diff_traces,
        latency_summary,
        validate_events,
    )

    tr_base, b = _traced(lambda: _twopc3().join())
    assert b.unique_state_count() == 288
    validate_events(tr_base.events)

    snap = str(tmp_path / "tr.ckpt")
    _kill_at(_twopc3, snap, 1)

    def resumed():
        c = _twopc3()
        c.resume_from(snap)
        return c.join()

    tr_res, r = _traced(resumed)
    assert r.unique_state_count() == 288
    validate_events(tr_res.events)
    assert any(e["ev"] == "restore" for e in tr_res.events)

    rep = diff_traces(tr_base.events, tr_res.events)
    assert rep["resume_wave_b"] is not None
    assert not rep["divergences"], rep["divergences"]
    assert rep["ok"]
    # a resumed run missing waves AFTER its resume point still fails
    truncated = [
        e for e in tr_res.events
        if not (e["ev"] == "wave"
                and e["wave"] == max(
                    w["wave"] for w in tr_res.events
                    if w["ev"] == "wave"
                ))
    ]
    rep2 = diff_traces(tr_base.events, truncated)
    assert any(d["field"] == "present" for d in rep2["divergences"])

    lat = latency_summary(tr_res.events)
    assert lat["profile"]["resumed_from_wave"] == \
        rep["resume_wave_b"]
    assert lat["profile"]["time_to_first_wave_sec"] >= 0

    # the report CLIs on the resumed trace: exit 0, no crash
    trace = str(tmp_path / "resumed.jsonl")
    tr_res.write_jsonl(trace)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for tool, needle in (
        ("latency_report.py", "RESUMED from wave"),
        ("mem_report.py", "resident-buffer ledger"),
    ):
        p = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, "tools", tool), trace],
            capture_output=True, text=True, env=env,
        )
        assert p.returncode == 0, (tool, p.stderr)
        assert needle in p.stdout, (tool, p.stdout)


def test_checkpoint_events_schema(tmp_path):
    """Traced checkpointed runs land schema-valid ``checkpoint`` /
    ``fault_injected`` / ``fault_recovery`` events."""
    from stateright_tpu.telemetry import validate_events

    snap = str(tmp_path / "ev.ckpt")

    def run():
        c = _twopc3(checkpoint_every=1, checkpoint_path=snap)
        c.retry_backoff_sec = 0.01
        faultinject.arm("raise", "mid_chunk", 1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return c.join()

    tr, c = _traced(run)
    assert c.unique_state_count() == 288
    validate_events(tr.events)
    kinds = {e["ev"] for e in tr.events}
    assert {"checkpoint", "fault_injected",
            "fault_recovery"} <= kinds
    ck = next(e for e in tr.events if e["ev"] == "checkpoint")
    assert ck["snapshot_bytes"] > 0 and ck["wave"] > 0


# -- the real process-kill cell (subprocess; crash_matrix's territory) ----


@pytest.mark.slow
def test_subprocess_kill_and_resume_cli(tmp_path):
    """The real thing: a CLI check lane killed by ``os._exit`` at a
    chunk boundary (STPU_FAULTS), resumed by a second process to the
    exact count — the crash matrix's kill cell, pinned here too."""
    snap = str(tmp_path / "cli.ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               STPU_FAULTS="kill@chunk_boundary:1")
    args = [sys.executable, "-m", "stateright_tpu", "2pc",
            "check-tpu", "3", "--waves-per-sync=2",
            "--checkpoint-every=1", f"--checkpoint-path={snap}"]
    p = subprocess.run(args, capture_output=True, text=True,
                       env=env, cwd=REPO_ROOT)
    assert p.returncode == faultinject.KILL_EXIT_CODE, p.stderr
    assert os.path.exists(snap)
    env.pop("STPU_FAULTS")
    p2 = subprocess.run(
        [sys.executable, "-m", "stateright_tpu", "2pc", "check-tpu",
         "3", "--waves-per-sync=2", "--resume",
         f"--checkpoint-path={snap}"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
    )
    assert p2.returncode == 0, p2.stderr
    assert "resuming from" in p2.stdout
    assert "unique=288" in p2.stdout
