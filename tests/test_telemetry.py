"""Run-telemetry gate (``pytest -m trace``).

Covers the tentpole surface end to end on CPU:

* the tracer core — spans, phase accumulators, activation exclusivity,
  JSONL round-trip + schema validation, Chrome-trace export;
* the engine wave log — a traced sparse sort-merge run produces one
  ``wave`` event per wave whose counters reconcile exactly with the
  checker's final counts, and tracing NEVER changes the counts (the
  smoke contract: traced paxos check == untraced paxos check);
* the sharded engine's log (psum'd global counters, enabled_pairs
  null), the deep level (one wave per chunk, real walls), the
  auto-budget retry event + warning, and the host-phase spans in the
  host checkers;
* the trace differ behind tools/trace_diff.py — wave alignment,
  per-phase regression thresholds, and the CLI's exit codes;
* the shared artifact numbering/provenance helper
  (stateright_tpu/artifacts.py) both exporters ride.
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu import artifacts, telemetry  # noqa: E402
from stateright_tpu.telemetry import (  # noqa: E402
    RunTracer,
    WAVE_LOG_FIELDS,
    diff_traces,
    format_diff,
    load_trace,
    validate_events,
    write_artifacts,
)

pytestmark = pytest.mark.trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _twopc_engine(rm=3, **kw):
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    kw.setdefault("capacity", 1 << 10)
    kw.setdefault("frontier_capacity", 256)
    kw.setdefault("cand_capacity", 1024)
    kw.setdefault("track_paths", False)
    return TwoPhaseSys(rm_count=rm).checker().spawn_tpu_sortmerge(**kw)


# -- tracer core ---------------------------------------------------------


def test_tracer_spans_events_and_roundtrip(tmp_path):
    tr = RunTracer()
    with tr.activate():
        assert telemetry.current_tracer() is tr
        tr.begin_run(lane=dict(engine="X"))
        with telemetry.span("compile", engine="X"):
            pass
        acc = tr.phase_acc("property_check")
        for _ in range(3):
            with acc:
                pass
        tr.event("auto_budget_retry", kind="cand_capacity",
                 old=8, new=64, attempt=1)
        tr.end_run(error=None, total_states=5, unique_states=5,
                   max_depth=2, duration_sec=0.01)
    assert telemetry.current_tracer() is None

    path = tr.write_jsonl(str(tmp_path / "t.jsonl"))
    evs = load_trace(path)
    validate_events(evs)
    kinds = [e["ev"] for e in evs]
    assert kinds[0] == "run_begin"
    assert "span" in kinds and "phase_total" in kinds
    assert kinds[-1] == "run_end"
    span = next(e for e in evs if e["ev"] == "span")
    assert span["phase"] == "compile" and span["dur"] >= 0
    acc_ev = next(e for e in evs if e["ev"] == "phase_total")
    assert acc_ev["phase"] == "property_check" and acc_ev["count"] == 3
    begin = evs[0]
    # provenance embedded in every run (the satellite contract)
    assert begin["provenance"]["jax"] == jax.__version__
    assert begin["provenance"]["backend"] == "cpu"
    assert begin["lane"] == {"engine": "X"}

    chrome = tr.write_chrome_trace(str(tmp_path / "t.trace.json"))
    ct = json.load(open(chrome))
    assert isinstance(ct["traceEvents"], list) and ct["traceEvents"]
    assert any(e.get("ph") == "X" for e in ct["traceEvents"])


def test_tracer_activation_is_exclusive():
    a, b = RunTracer(), RunTracer()
    with a.activate():
        with pytest.raises(RuntimeError):
            with b.activate():
                pass
    # released after exit
    with b.activate():
        assert telemetry.current_tracer() is b


def test_span_is_noop_without_tracer():
    with telemetry.span("anything"):
        pass
    telemetry.emit("ignored", x=1)  # no tracer: swallowed


def test_validate_rejects_inconsistent_wave_counters(tmp_path):
    tr = RunTracer()
    with tr.activate():
        tr.begin_run()
        tr.record_chunk(
            chunk=0, wave0=0, t0=0.0, t1=1.0,
            dispatch_sec=0.1, fetch_sec=0.9,
            wave_rows=np.array([[1, 2, 2, 2, 10, 1, 0, 0],
                                [2, 4, 4, 4, 99, 2, 0, 0]]),
        )
        tr.end_run()
    with pytest.raises(ValueError, match="unique_total"):
        validate_events(tr.events)


def test_unknown_level_rejected():
    with pytest.raises(ValueError):
        RunTracer(level="verbose")


# -- engine wave log (single chip) ---------------------------------------


def test_traced_run_counts_unchanged_and_schema_valid(tmp_path):
    """The smoke contract: a traced sparse engine run explores the
    SAME space as an untraced one and its artifacts are schema-valid
    (the paxos lane rides the identical code path; see
    test_trace_smoke_paxos for the paxos-shaped version)."""
    c0 = _twopc_engine().join()
    tr = RunTracer()
    with tr.activate():
        c1 = _twopc_engine().join()
    assert c1.unique_state_count() == c0.unique_state_count() == 288
    assert c1.state_count() == c0.state_count()

    jsonl, chrome = write_artifacts(tr, root=str(tmp_path))
    assert os.path.basename(jsonl).startswith("TRACE_r")
    evs = load_trace(jsonl)
    validate_events(evs)
    waves = [e for e in evs if e["ev"] == "wave"]
    assert waves, "a traced engine run must produce wave events"
    # exact reconciliation with the checker's final counters
    assert waves[-1]["unique_total"] == c1.unique_state_count()
    n0 = waves[0]["unique_total"] - waves[0]["new_states"]
    assert n0 + sum(w["new_states"] for w in waves) == (
        c1.unique_state_count()
    )
    assert n0 + sum(w["candidates"] for w in waves) == c1.state_count()
    assert waves[0]["depth"] == 1
    assert all(w["enabled_pairs"] >= w["candidates"] for w in waves)
    for field in WAVE_LOG_FIELDS:
        assert field in waves[0]
    # lane config names the engine and its budgets
    lane = evs[0]["lane"]
    assert lane["engine"] == "SortMergeTpuBfsChecker"
    assert lane["sparse"] is True
    ct = json.load(open(chrome))
    assert any(e.get("name", "").startswith("wave")
               for e in ct["traceEvents"])


def test_trace_smoke_paxos(tmp_path):
    """Traced ``paxos check`` smoke on CPU (the tier-1-sized 2-client
    lane; the full check-3/check-4 shapes run the identical traced
    program and are exercised by the slow-marked test below): JSONL +
    Chrome artifacts, identical state counts to untraced."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    def spawn():
        return (
            paxos_model(PaxosModelCfg(client_count=2, server_count=3))
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << 15,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )
        )

    c0 = spawn().join()
    tr = RunTracer()
    with tr.activate():
        c1 = spawn().join()
    assert c1.unique_state_count() == c0.unique_state_count() == 16668
    jsonl, chrome = write_artifacts(tr, root=str(tmp_path))
    evs = load_trace(jsonl)
    validate_events(evs)
    waves = [e for e in evs if e["ev"] == "wave"]
    assert waves[-1]["unique_total"] == 16668
    assert json.load(open(chrome))["traceEvents"]


@pytest.mark.slow
def test_trace_smoke_paxos_check_3(tmp_path):
    """The full satellite smoke at `paxos check 3` scale (1,194,428
    states on CPU — slow-marked)."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
    from stateright_tpu.models.paxos_tpu import STRUCTURAL_SIZES

    def spawn():
        return (
            paxos_model(PaxosModelCfg(client_count=3, server_count=3))
            .checker()
            .spawn_tpu_sortmerge(
                track_paths=False, cand_capacity=1 << 22,
                **STRUCTURAL_SIZES[3],
            )
        )

    c0 = spawn().join()
    tr = RunTracer()
    with tr.activate():
        c1 = spawn().join()
    assert c1.unique_state_count() == c0.unique_state_count() == 1194428
    jsonl, _ = write_artifacts(tr, root=str(tmp_path))
    evs = load_trace(jsonl)
    validate_events(evs)
    waves = [e for e in evs if e["ev"] == "wave"]
    assert waves[-1]["unique_total"] == 1194428


def test_deep_level_gives_real_per_wave_walls():
    tr = RunTracer(level="deep")
    with tr.activate():
        c = _twopc_engine().join()
    assert c.unique_state_count() == 288
    chunks = [e for e in tr.events if e["ev"] == "chunk"]
    waves = [e for e in tr.events if e["ev"] == "wave"]
    assert len(chunks) == len(waves)  # one wave per chunk
    assert all(ch["device_sec"] is not None for ch in chunks)
    assert all(w["t_est"] is False for w in waves)
    assert any(e["ev"] == "deep_sync_override" for e in tr.events)


def test_traced_sharded_engine_wave_log():
    from jax.sharding import Mesh

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs the virtual multi-device CPU mesh")
    mesh = Mesh(np.array(devices[:4]), ("shard",))
    tr = RunTracer()
    with tr.activate():
        c = (
            TwoPhaseSys(rm_count=3)
            .checker()
            .spawn_tpu_sharded_sortmerge(
                mesh=mesh,
                capacity=1 << 10,
                frontier_capacity=256,
                cand_capacity=1024,
                bucket_capacity=512,
                waves_per_sync=8,
                track_paths=False,
            )
            .join()
        )
    assert c.unique_state_count() == 288
    validate_events(tr.events)
    waves = [e for e in tr.events if e["ev"] == "wave"]
    assert waves and waves[-1]["unique_total"] == 288
    # global (psum'd) frontier rows, not per-shard
    assert waves[0]["frontier_rows"] == 1
    # the GLOBAL log wrapper still can't see the enabled popcount,
    # but the per-shard mesh log can: the wave event's enabled_pairs
    # is back-filled from the shard sum (the round-11 hole closure)
    shard_waves = [e for e in tr.events if e["ev"] == "shard_wave"]
    assert shard_waves
    for w in waves:
        rows = [e for e in shard_waves if e["wave"] == w["wave"]]
        assert len(rows) == 4
        assert w["enabled_pairs"] == sum(
            r["enabled_pairs"] for r in rows
        )
        assert w["enabled_pairs"] >= w["candidates"]
    assert tr.events[0]["lane"]["n_shards"] == 4
    assert tr.events[0]["lane"]["dest_tile_lanes"] > 0


def test_traced_sharded_parity_and_shard_log_8_mesh():
    """The round-11 acceptance gate: on the virtual 8-CPU mesh, a
    TRACED sharded run explores exactly the space an untraced one does
    (the per-shard log must not perturb the search), every wave gets
    one ``shard_wave`` event per shard, the per-shard counters
    reconcile with the global log lane for lane, and the derived
    shard_balance summary agrees with the engine's own shuffle
    metric."""
    from jax.sharding import Mesh

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry import shard_balance

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the virtual 8-device CPU mesh")
    mesh = Mesh(np.array(devices[:8]), ("shard",))

    def spawn():
        return (
            TwoPhaseSys(rm_count=3)
            .checker()
            .spawn_tpu_sharded_sortmerge(
                mesh=mesh,
                capacity=1 << 10,
                frontier_capacity=256,
                cand_capacity=1024,
                bucket_capacity=512,
                waves_per_sync=8,
                track_paths=False,
            )
        )

    c0 = spawn().join()
    tr = RunTracer()
    with tr.activate():
        c1 = spawn().join()
    assert c1.unique_state_count() == c0.unique_state_count() == 288
    assert c1.state_count() == c0.state_count()
    validate_events(tr.events)
    waves = {e["wave"]: e for e in tr.events if e["ev"] == "wave"}
    shard_waves = [e for e in tr.events if e["ev"] == "shard_wave"]
    assert waves and shard_waves
    for w, ev in waves.items():
        rows = [e for e in shard_waves if e["wave"] == w]
        assert len(rows) == 8
        assert sum(r["frontier_rows"] for r in rows) == \
            ev["frontier_rows"]
        assert sum(r["candidates"] for r in rows) == ev["candidates"]
        assert sum(r["new_states"] for r in rows) == ev["new_states"]
        assert sum(r["visited_total"] for r in rows) == \
            ev["unique_total"]
        # the Bd cap gates all_to_all correctness: a logged fill can
        # never exceed it on a completed (non-overflow) run
        assert all(r["dest_fill_peak"] <= r["dest_cap"] for r in rows)
    bal = shard_balance(tr.events)
    assert bal is not None and bal["n_shards"] == 8
    assert bal["waves"] == len(waves)
    assert sum(bal["visited_per_shard"]) == 288
    # trace-derived routed volume == the engine's psum'd shuffle
    # counter (two independent paths to the same number)
    assert bal["routed_rows_total"] == c1.metrics["shuffle_volume"]
    # a self-diff of the sharded trace is clean (shard-aware
    # alignment included)
    rep = diff_traces(tr.events, tr.events)
    assert rep["ok"], rep["divergences"]


def test_traced_sharded_hash_engine_shard_log():
    """The hash-table sharded engine (parallel/engine.py) grew BOTH
    logs in round 11 — it previously traced chunk events only. Counts
    unchanged, wave events present, shard rows reconcile."""
    from jax.sharding import Mesh

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs the virtual multi-device CPU mesh")
    mesh = Mesh(np.array(devices[:4]), ("shard",))

    def spawn():
        return (
            TwoPhaseSys(rm_count=3)
            .checker()
            .spawn_tpu_sharded(
                mesh=mesh,
                capacity=1 << 10,
                frontier_capacity=256,
                cand_capacity=1024,
                bucket_capacity=512,
                waves_per_sync=8,
                track_paths=False,
            )
        )

    c0 = spawn().join()
    tr = RunTracer()
    with tr.activate():
        c1 = spawn().join()
    assert c1.unique_state_count() == c0.unique_state_count() == 288
    validate_events(tr.events)
    waves = [e for e in tr.events if e["ev"] == "wave"]
    shard_waves = [e for e in tr.events if e["ev"] == "shard_wave"]
    assert waves[-1]["unique_total"] == 288
    for w in waves:
        rows = [e for e in shard_waves if e["wave"] == w["wave"]]
        assert len(rows) == 4
        assert sum(r["new_states"] for r in rows) == w["new_states"]
        assert sum(r["visited_total"] for r in rows) == \
            w["unique_total"]


def test_auto_budget_retry_event_and_warning(tmp_path):
    """Satellite: a forced overflow on the geometric capacity ladder
    must produce a telemetry event AND a one-line warning naming the
    old/new capacity (the retry used to be silent)."""
    tr = RunTracer()
    with tr.activate(), warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        c = _twopc_engine(cand_capacity="auto")
        c._budget_store = lambda: str(tmp_path / "budgets.json")
        c.cand_capacity = 8  # force the first wave over budget
        c.join()
    assert c.unique_state_count() == 288
    msgs = [str(w.message) for w in rec
            if "auto-budget" in str(w.message)]
    assert msgs and "8 ->" in msgs[0]
    evs = [e for e in tr.events if e["ev"] == "auto_budget_retry"]
    assert evs and evs[0]["old"] == 8 and evs[0]["new"] > 8
    assert evs[0]["kind"] == "cand_capacity"
    # the clean re-run's waves overwrite the failed attempt's indices:
    # the final wave still reconciles
    waves = [e for e in tr.events if e["ev"] == "wave"]
    assert waves[-1]["unique_total"] == 288
    # a retried run's trace is a LEGITIMATE artifact: the validator
    # treats a non-advancing wave index as an attempt restart (and
    # trace_diff's last-occurrence alignment reads the clean attempt)
    validate_events(tr.events)
    rep = diff_traces(tr.events, tr.events)
    assert not rep["divergences"]


def test_untraced_run_keeps_wave_log_out_of_carry():
    c = _twopc_engine()
    c.keep_final_carry = True
    c.join()
    assert "wlog" not in c._final_carry
    assert "wv_pairs" not in c._final_carry


# -- host-phase spans ----------------------------------------------------


def test_host_bfs_phase_totals_and_reconstruction_span():
    from stateright_tpu.models.increment import Increment

    tr = RunTracer()
    with tr.activate():
        c = Increment(thread_count=2).checker().spawn_bfs().join()
    assert "fin" in c.discoveries()
    kinds = {e["ev"] for e in tr.events}
    assert {"run_begin", "run_end"} <= kinds
    totals = {e["phase"] for e in tr.events if e["ev"] == "phase_total"}
    assert "property_check" in totals
    spans = {e["phase"] for e in tr.events if e["ev"] == "span"}
    assert "counterexample_reconstruction" in spans
    end = next(e for e in tr.events if e["ev"] == "run_end")
    assert end["unique_states"] == c.unique_state_count()
    assert end["error"] is None


def test_host_dfs_symmetry_span():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    tr = RunTracer()
    with tr.activate():
        TwoPhaseSys(rm_count=2).checker().symmetry().spawn_dfs().join()
    totals = {e["phase"] for e in tr.events if e["ev"] == "phase_total"}
    assert "symmetry_canonicalization" in totals
    assert "property_check" in totals


def test_device_engine_spans_and_chunk_split():
    tr = RunTracer()
    with tr.activate():
        _twopc_engine().join()
    spans = {e["phase"] for e in tr.events if e["ev"] == "span"}
    assert {"compile", "seed_upload"} <= spans
    chunks = [e for e in tr.events if e["ev"] == "chunk"]
    assert chunks
    for ch in chunks:
        assert ch["dispatch_sec"] >= 0 and ch["fetch_sec"] >= 0
        assert ch["device_sec"] is None  # default level: no extra sync


def test_failed_run_ends_with_error():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    tr = RunTracer()
    with tr.activate():
        c = (
            TwoPhaseSys(rm_count=3)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=64, frontier_capacity=64, cand_capacity=256,
                track_paths=False,
            )
        )
        with pytest.raises(RuntimeError, match="overflow"):
            c.join()
    end = next(e for e in tr.events if e["ev"] == "run_end")
    assert end["error"] and "overflow" in end["error"]


# -- trace diff ----------------------------------------------------------


def _synthetic_trace(tmp_path, name, *, fetch=0.9, new=(9, 40, 100),
                     total=2.0):
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane=dict(engine="T"))
        with telemetry.span("compile"):
            pass
        u = 1
        rows = []
        for i, n in enumerate(new):
            u += n
            rows.append([max(n, 1), n + 2, n + 1, n, u, i + 1, 0, 0])
        tr.record_chunk(
            chunk=0, wave0=0, t0=0.0, t1=1.0,
            dispatch_sec=0.01, fetch_sec=fetch,
            wave_rows=np.array(rows),
        )
        tr.end_run(error=None, total_states=sum(new), unique_states=u,
                   max_depth=len(new), duration_sec=total)
    path = str(tmp_path / name)
    tr.write_jsonl(path)
    return path


def test_trace_diff_clean_and_regression(tmp_path):
    a = load_trace(_synthetic_trace(tmp_path, "a.jsonl"))
    b = load_trace(_synthetic_trace(tmp_path, "b.jsonl"))
    rep = diff_traces(a, b)
    assert rep["ok"] and not rep["divergences"]
    assert "verdict: OK" in format_diff(rep)

    slow = load_trace(
        _synthetic_trace(tmp_path, "slow.jsonl", fetch=2.0, total=4.0)
    )
    rep2 = diff_traces(a, slow)
    assert not rep2["ok"]
    assert "host_fetch" in rep2["regressions"]
    assert "run_total" in rep2["regressions"]
    assert "REGRESSION" in format_diff(rep2)
    # the faster direction is not a regression
    assert diff_traces(slow, a)["ok"]


def test_trace_diff_wave_divergence(tmp_path):
    a = load_trace(_synthetic_trace(tmp_path, "a.jsonl"))
    d = load_trace(
        _synthetic_trace(tmp_path, "d.jsonl", new=(9, 41, 100))
    )
    rep = diff_traces(a, d)
    assert not rep["ok"]
    fields = {x["field"] for x in rep["divergences"]}
    assert "new_states" in fields and "unique_total" in fields
    assert "DIVERGENCE" in format_diff(rep)


def test_trace_diff_cli_exit_codes(tmp_path):
    a = _synthetic_trace(tmp_path, "a.jsonl")
    b = _synthetic_trace(tmp_path, "b.jsonl", fetch=2.0, total=4.0)
    tool = os.path.join(REPO_ROOT, "tools", "trace_diff.py")

    def run(*argv):
        return subprocess.run(
            [sys.executable, tool, *argv],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )

    ok = run(a, a)
    assert ok.returncode == 0, ok.stderr
    assert "verdict: OK" in ok.stdout

    reg = run(a, b)
    assert reg.returncode == 1
    assert "REGRESSION" in reg.stdout

    loose = run(a, b, "--threshold", "10.0")
    assert loose.returncode == 0

    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as fh:
        fh.write("not json\n")
    assert run(a, bad).returncode == 2


# -- mesh observability: shard_wave / shard_balance / shard_report -------


def _synthetic_shard_trace(tmp_path, name, per_shard_new, *,
                           permute=False, dest_cap=512, fill=8,
                           capacity=1024, visited0=1,
                           visited_exact=True):
    """A schema-valid sharded trace: ``per_shard_new[wave][shard]`` is
    the post-dedup new count; global rows are the shard sums, so the
    two log levels reconcile the way a real engine's do. ``permute``
    reverses the shard numbering (the relabeling the multiset
    alignment must tolerate); ``fill``/``dest_cap`` set the dest-tile
    lanes; ``visited0`` seeds each shard's visited counter."""
    tr = RunTracer()
    with tr.activate():
        S = len(per_shard_new[0])
        tr.begin_run(lane=dict(engine="T", n_shards=S,
                               capacity=capacity, dest_tile_lanes=10,
                               visited_exact=visited_exact))
        visited = [visited0] * S
        prev_front = [1] * S
        u = S * visited0
        rows_g, rows_s = [], []
        for i, new in enumerate(per_shard_new):
            cand = [n * 2 for n in new]
            u += sum(new)
            rows_g.append([sum(prev_front), sum(cand), sum(cand),
                           sum(new), u, i + 1, 0, 0])
            wave_rows = []
            for s in range(S):
                visited[s] += new[s]
                wave_rows.append([
                    prev_front[s], cand[s], cand[s],
                    cand[s] // 2, cand[s], fill, dest_cap,
                    new[s], visited[s],
                ])
            rows_s.append(wave_rows)
            prev_front = new
        sr = np.array(rows_s).transpose(1, 0, 2)  # [S, waves, lanes]
        if permute:
            sr = sr[::-1]
        tr.record_chunk(
            chunk=0, wave0=0, t0=0.0, t1=1.0,
            dispatch_sec=0.01, fetch_sec=0.9,
            wave_rows=np.array(rows_g), shard_rows=sr,
        )
        tr.end_run(error=None, total_states=u, unique_states=u,
                   max_depth=len(per_shard_new), duration_sec=2.0)
    path = str(tmp_path / name)
    tr.write_jsonl(path)
    return path


BALANCED = [[8, 8, 8, 8], [40, 40, 40, 40], [100, 100, 100, 100]]


def test_shard_wave_schema_valid_and_chrome_tracks(tmp_path):
    from stateright_tpu.telemetry import SHARD_LOG_FIELDS

    path = _synthetic_shard_trace(tmp_path, "s.jsonl", BALANCED)
    evs = load_trace(path)
    validate_events(evs)
    sws = [e for e in evs if e["ev"] == "shard_wave"]
    assert len(sws) == 3 * 4
    for field in SHARD_LOG_FIELDS:
        assert field in sws[0]
    # schema rejection: a broken per-shard running sum
    bad = [dict(e) for e in evs]
    victim = next(e for e in bad if e["ev"] == "shard_wave"
                  and e["wave"] == 2)
    victim["visited_total"] += 1
    with pytest.raises(ValueError, match="visited_total"):
        validate_events(bad)
    # missing-field rejection
    bad2 = [dict(e) for e in evs]
    del next(e for e in bad2
             if e["ev"] == "shard_wave")["routed_rows"]
    with pytest.raises(ValueError, match="routed_rows"):
        validate_events(bad2)
    # Chrome export renders one track per shard
    tr = RunTracer()
    tr.events = evs
    chrome = tr.write_chrome_trace(str(tmp_path / "s.trace.json"))
    ct = json.load(open(chrome))
    names = {e["args"]["name"] for e in ct["traceEvents"]
             if e.get("name") == "thread_name"}
    assert {"shard 0", "shard 3"} <= names


def test_shard_balance_flags_deliberate_imbalance(tmp_path):
    """The skew-metric satellite: one shard carrying the whole big
    waves must flag, a balanced run must not."""
    from stateright_tpu.telemetry import shard_balance

    ok = load_trace(
        _synthetic_shard_trace(tmp_path, "ok.jsonl", BALANCED)
    )
    bal = shard_balance(ok)
    assert bal["n_shards"] == 4 and bal["waves"] == 3
    assert bal["frontier_skew_weighted"] == 1.0
    assert not any("imbalance" in w for w in bal["warnings"])

    skewed = load_trace(
        _synthetic_shard_trace(
            tmp_path, "skew.jsonl",
            [[8, 8, 8, 8], [400, 0, 0, 0], [400, 0, 0, 0]],
        )
    )
    bal2 = shard_balance(skewed)
    assert bal2["frontier_skew_worst"]["skew"] == 4.0
    assert bal2["frontier_skew_weighted"] > 2.0
    assert any("imbalance" in w for w in bal2["warnings"])
    # routed volume prices bytes off the lane's tile width
    assert bal2["routed_bytes_total"] == \
        bal2["routed_rows_total"] * 10 * 4


def test_shard_balance_headroom_warnings(tmp_path):
    """dest-tile fill near the lossless Bd cap and a shard's visited
    occupancy near capacity both warn, via the SHARED formatter
    (stateright_tpu/occupancy.py)."""
    from stateright_tpu.telemetry import shard_balance

    tight = load_trace(
        _synthetic_shard_trace(
            tmp_path, "tight.jsonl", BALANCED,
            dest_cap=100, fill=95, capacity=200, visited0=40,
        )
    )
    bal = shard_balance(tight)
    assert bal["dest_fill_worst"]["util"] == 0.95
    assert any("dest tile" in w and "bucket_capacity" in w
               for w in bal["warnings"])
    assert bal["occupancy_max"] is not None
    assert any("visited array" in w and "overflows exactly" in w
               for w in bal["warnings"])

    # a HASH-engine lane (visited_exact=False) watches probe
    # pressure instead: warns earlier (0.7 bar) with the
    # open-addressing failure mode, not exact-capacity headroom
    probing = load_trace(
        _synthetic_shard_trace(
            tmp_path, "probe.jsonl", BALANCED,
            capacity=200, visited0=40, visited_exact=False,
        )
    )
    bal2 = shard_balance(probing)
    assert any("probe failures" in w for w in bal2["warnings"])
    assert not any("overflows exactly" in w for w in bal2["warnings"])
    # at ~0.37 occupancy an exact-capacity lane is quiet where the
    # probe watch would also be — threshold semantics, not noise
    mid = load_trace(
        _synthetic_shard_trace(
            tmp_path, "mid.jsonl", BALANCED,
            capacity=200, visited0=11, visited_exact=False,
        )
    )
    # 11 + 148 = 159/200 = 0.795 > 0.7: the probe watch fires where
    # the exact-capacity watch (0.8 bar) would stay quiet
    bal3 = shard_balance(mid)
    assert any("probe failures" in w for w in bal3["warnings"])
    mid_exact = load_trace(
        _synthetic_shard_trace(
            tmp_path, "mid_exact.jsonl", BALANCED,
            capacity=200, visited0=11, visited_exact=True,
        )
    )
    assert not any("visited array" in w
                   for w in shard_balance(mid_exact)["warnings"])


def test_occupancy_warning_shared_helper():
    """The deduplicated occupancy formatter: one home for the
    hash-engine probe-pressure warning AND the mesh report's
    exact-capacity headroom warnings."""
    from stateright_tpu.occupancy import (
        HEADROOM_THRESHOLD,
        occupancy_warning,
    )

    assert occupancy_warning(0.5) is None
    msg = occupancy_warning(0.8, used=800, capacity=1000)
    assert "80% full" in msg and "(800/1000)" in msg
    assert "probe failures" in msg  # the hash-engine default
    custom = occupancy_warning(
        0.95, kind="shard 3 visited array",
        threshold=HEADROOM_THRESHOLD,
        consequence="overflows at 100%",
    )
    assert custom.startswith("shard 3 visited array")
    assert "overflows at 100%" in custom
    # at-threshold is quiet (warn past, not at)
    assert occupancy_warning(HEADROOM_THRESHOLD,
                             threshold=HEADROOM_THRESHOLD) is None


def test_hash_engine_occupancy_warning_uses_helper():
    """checkers/tpu.py's probe-pressure warning now routes through
    the shared formatter (the dedup satellite) — same text, same
    threshold, absolute counts included."""
    from stateright_tpu.checkers.tpu import TpuBfsChecker
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = TwoPhaseSys(rm_count=3).checker().spawn_tpu(
        capacity=1 << 10, frontier_capacity=256, track_paths=False,
    )
    assert isinstance(c, TpuBfsChecker)
    c._unique_states = 800
    c.total_capacity = 1000
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        c._maybe_warn_occupancy(0.8)
        c._maybe_warn_occupancy(0.5)  # under threshold: quiet
    msgs = [str(w.message) for w in rec]
    assert len(msgs) == 1
    assert "visited table 80% full (800/1000)" in msgs[0]
    assert "probe failures" in msgs[0]


def test_trace_diff_shard_aware_alignment(tmp_path):
    """Shard-aware wave alignment: shard RENUMBERING must not
    false-positive (multiset comparison), a redistributed partition
    with identical GLOBAL counters must still diverge."""
    a = load_trace(_synthetic_shard_trace(tmp_path, "a.jsonl",
                                          BALANCED))
    # same rows, shards relabeled in reverse — a mesh relabeling
    perm = load_trace(
        _synthetic_shard_trace(tmp_path, "p.jsonl", BALANCED,
                               permute=True)
    )
    rep = diff_traces(a, perm)
    assert rep["ok"], rep["divergences"]

    # dest_cap is CONFIG, not exploration: a bucket_capacity-only
    # A/B (different Bd, same counts) must compare on timing, not
    # fail the alignment gate
    retuned = load_trace(
        _synthetic_shard_trace(tmp_path, "cap.jsonl", BALANCED,
                               dest_cap=2048)
    )
    assert diff_traces(a, retuned)["ok"]

    # redistribute wave 2 across shards: global sums identical, the
    # per-shard partition is not → shard_multiset divergence
    moved = load_trace(
        _synthetic_shard_trace(
            tmp_path, "m.jsonl",
            [[8, 8, 8, 8], [40, 40, 40, 40], [130, 70, 100, 100]],
        )
    )
    rep2 = diff_traces(a, moved)
    assert not rep2["ok"]
    fields = {d["field"] for d in rep2["divergences"]}
    # the redistribution preserves every GLOBAL counter — only the
    # shard-aware layer catches it
    assert fields == {"shard_multiset"}
    # one side sharded, the other not → shard_present divergence
    unsharded = load_trace(_synthetic_trace(tmp_path, "u.jsonl",
                                            new=(32, 160, 400)))
    rep3 = diff_traces(a, unsharded)
    assert not rep3["ok"]
    assert any(d["field"] == "shard_present"
               for d in rep3["divergences"])


def test_trace_diff_cli_shard_exit_codes(tmp_path):
    """The satellite's exit-code contract, through the real CLI."""
    a = _synthetic_shard_trace(tmp_path, "a.jsonl", BALANCED)
    perm = _synthetic_shard_trace(tmp_path, "p.jsonl", BALANCED,
                                  permute=True)
    moved = _synthetic_shard_trace(
        tmp_path, "m.jsonl",
        [[8, 8, 8, 8], [40, 40, 40, 40], [130, 70, 100, 100]],
    )
    tool = os.path.join(REPO_ROOT, "tools", "trace_diff.py")

    def run(*argv):
        return subprocess.run(
            [sys.executable, tool, *argv],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )

    assert run(a, perm).returncode == 0  # renumbering: clean
    div = run(a, moved)
    assert div.returncode == 1
    assert "shard_multiset" in div.stdout


def test_shard_report_cli(tmp_path):
    tool = os.path.join(REPO_ROOT, "tools", "shard_report.py")

    def run(*argv):
        return subprocess.run(
            [sys.executable, tool, *argv],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )

    skewed = _synthetic_shard_trace(
        tmp_path, "skew.jsonl",
        [[8, 8, 8, 8], [400, 0, 0, 0], [400, 0, 0, 0]],
    )
    out = run(skewed)
    assert out.returncode == 0, out.stderr
    assert "shard balance: run #0, 4 shards" in out.stdout
    assert "worst-wave skew" in out.stdout
    assert "cumulative shuffle" in out.stdout
    assert "WARNING" in out.stdout  # the skew warning surfaces

    # a trace without shard events is a usage error, not a crash
    plain = _synthetic_trace(tmp_path, "plain.jsonl")
    bad = run(plain)
    assert bad.returncode == 2
    assert "no shard_wave events" in bad.stderr


# -- CLI flag ------------------------------------------------------------


def test_cli_pop_trace_flag():
    from stateright_tpu.cli import _pop_trace_flag

    assert _pop_trace_flag(["paxos", "check", "2"]) == (
        None, ["paxos", "check", "2"]
    )
    assert _pop_trace_flag(["paxos", "--trace", "check-tpu", "4"]) == (
        "default", ["paxos", "check-tpu", "4"]
    )
    assert _pop_trace_flag(["2pc", "check-tpu", "3", "--trace=deep"]) == (
        "deep", ["2pc", "check-tpu", "3"]
    )


def test_cli_rejects_unknown_trace_level():
    from stateright_tpu import cli

    with pytest.raises(SystemExit, match="verbose"):
        cli.main(["increment", "check-tpu", "2", "--trace=verbose"])


def test_cli_trace_writes_artifacts_on_failure(tmp_path, monkeypatch):
    """A traced run that raises must still leave its partial trace
    (the failure is what the trace is for)."""
    from stateright_tpu import cli

    monkeypatch.setattr(artifacts, "repo_root", lambda: str(tmp_path))

    def boom(sub, args):
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
            capacity=64, frontier_capacity=64, cand_capacity=256,
            track_paths=False,
        ).join()

    monkeypatch.setitem(cli._MODELS, "2pc", (boom, ["check-tpu"]))
    with pytest.raises(RuntimeError, match="overflow"):
        cli.main(["2pc", "check-tpu", "3", "--trace"])
    written = os.listdir(tmp_path)
    assert any(f.startswith("TRACE_r") and f.endswith(".jsonl")
               for f in written)
    jsonl = next(f for f in written if f.endswith(".jsonl"))
    evs = load_trace(str(tmp_path / jsonl))
    end = next(e for e in evs if e["ev"] == "run_end")
    assert end["error"] and "overflow" in end["error"]


def test_cli_trace_writes_artifacts(tmp_path, monkeypatch, capsys):
    from stateright_tpu import cli

    monkeypatch.setattr(artifacts, "repo_root", lambda: str(tmp_path))
    cli.main(["increment", "check-tpu", "2", "--trace"])
    out = capsys.readouterr()
    assert "Done." in out.out
    written = sorted(os.listdir(tmp_path))
    assert any(f.startswith("TRACE_r") and f.endswith(".jsonl")
               for f in written)
    assert any(f.endswith(".trace.json") for f in written)
    jsonl = next(f for f in written if f.endswith(".jsonl"))
    evs = load_trace(str(tmp_path / jsonl))
    validate_events(evs)
    assert any(e["ev"] == "wave" for e in evs)


# -- shared artifact numbering / provenance ------------------------------


def test_artifact_numbering_shared_across_families(tmp_path):
    root = str(tmp_path)
    assert artifacts.next_round(root) == 1
    open(os.path.join(root, "BENCH_r03.json"), "w").close()
    open(os.path.join(root, "TRACE_r05.jsonl"), "w").close()
    assert artifacts.next_round(root) == 6
    assert artifacts.artifact_path("LINT", "json", root=root).endswith(
        "LINT_r06.json"
    )
    p = artifacts.artifact_path("TRACE", "trace.json", root=root,
                                round=9)
    assert p.endswith("TRACE_r09.trace.json")


def test_lint_cli_uses_shared_numbering(tmp_path, monkeypatch):
    """tools/lint_kernels.py --json and the trace exporter share ONE
    numbering helper: both consult every artifact family."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_kernels", os.path.join(REPO_ROOT, "tools",
                                     "lint_kernels.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the module must not have grown a private numbering copy back
    assert not hasattr(mod, "_next_artifact_path")


def test_provenance_block():
    p = artifacts.provenance(lane={"headline": "x"})
    assert p["jax"] == jax.__version__
    assert p["backend"] == "cpu"
    assert p["device_count"] >= 1
    assert p["python"]
    assert p["lane"] == {"headline": "x"}
    # the repo is a git checkout: the SHA must resolve
    assert p["git_sha"] and len(p["git_sha"]) == 40
