"""The wave-wall profiler subsystem (stateright_tpu/wavewall.py):
the out-of-stage attribution VERDICT r5 item 1 asked for, pinned to
run on CPU CI — capture a mid-run carry, re-time one wave body,
measure the identity-switch carry baseline, and emit the per-HLO-
category op/byte breakdown."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.two_phase_commit import TwoPhaseSys  # noqa: E402
from stateright_tpu.wavewall import (  # noqa: E402
    format_report,
    hlo_category,
    parse_hlo_categories,
    wave_wall_report,
)


def test_hlo_category_vocabulary():
    assert hlo_category("copy") == "data formatting"
    assert hlo_category("transpose") == "data formatting"
    assert hlo_category("pad") == "quantization padding"
    assert hlo_category("dynamic-update-slice") == "dynamic-update-slice"
    assert hlo_category("dynamic-slice") == "carry/slice movement"
    assert hlo_category("concatenate") == "carry/slice movement"
    assert hlo_category("sort") == "sort"
    assert hlo_category("gather") == "gather"
    assert hlo_category("fusion") == "fusion"
    assert hlo_category("add") == "elementwise compute"
    assert hlo_category("while") == "control"


def test_parse_hlo_categories_counts_and_bytes():
    text = "\n".join(
        [
            "HloModule jit_body",
            "ENTRY %main (p0: u32[8,4]) -> u32[8,4] {",
            "  %p0 = u32[8,4]{1,0} parameter(0)",
            "  %c = u32[8,4]{1,0} copy(%p0)",
            "  %s = (u32[128]{0}, u32[128]{0}) sort(%a, %b), dimensions={0}",
            "  %a2 = u32[128]{0} add(%x, %y)",
            "  ROOT %t = u32[8,4]{1,0} copy(%c)",
            "}",
        ]
    )
    cats = parse_hlo_categories(text)
    assert cats["data formatting"]["ops"] == 2
    assert cats["data formatting"]["bytes"] == 2 * 8 * 4 * 4
    assert cats["sort"]["ops"] == 1
    assert cats["sort"]["bytes"] == 2 * 128 * 4
    assert cats["elementwise compute"]["ops"] == 1
    assert cats["control"]["ops"] == 1  # the parameter


def test_wave_wall_report_on_cpu():
    """End-to-end on a real captured carry: the report carries the
    wall/carry-baseline timings and a non-empty category breakdown
    whose data-movement categories are populated (the wave writes
    class-local blocks via dynamic-update-slice by design)."""
    c = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .target_state_count(800)
        .spawn_tpu_sortmerge(
            capacity=1 << 11,
            frontier_capacity=1 << 9,
            cand_capacity=1 << 11,
            track_paths=False,
        )
    )
    c.keep_final_carry = True
    c.join()
    rep = wave_wall_report(c, reps=2)
    assert rep["n_rows"] > 0
    assert rep["wave_ms"] >= 0.0
    assert np.isfinite(rep["loop_floor_ms"])
    cats = rep["categories"]
    assert cats, "empty category breakdown"
    assert "dynamic-update-slice" in cats
    assert any(s["bytes"] > 0 for s in cats.values())
    # The engine path must stay scatter-free (the repo's core design
    # claim — PERF.md: XLA:TPU serializes scatters).
    assert "scatter" not in cats
    text = format_report(rep, stage_sum_ms=1.0)
    assert "hlo category" in text and "out-of-stage" in text


def test_merge_stage_estimate_smoke():
    """The bench-facing merge-stage attribution (round 10): runs off
    nothing but a finished checker, reports every stage key positive
    and the impl the checker ran — pinned here so a metrics/ladder/
    ops rename can't keep tier-1 green while crashing bench.py at
    the pending BENCH_r06 chip run."""
    from stateright_tpu.wavewall import merge_stage_estimate

    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 10,
            frontier_capacity=1 << 8,
            cand_capacity=1 << 10,
            track_paths=False,
        )
    )
    est = merge_stage_estimate(c, reps=2)
    assert est["impl"] == c.merge_impl
    assert est["V_v"] > 0 and est["B"] > 0 and est["NF"] > 0
    for k in ("cand_sort_ms", "member_ms", "winner_compact_ms",
              "append_ms", "rebuild_sort_ms"):
        assert est[k] >= 0.0, k
    assert est["dedup_ms"] == pytest.approx(
        est["cand_sort_ms"] + est["member_ms"]
        + est["winner_compact_ms"] + est["append_ms"], abs=0.01,
    )
