"""Explorer handlers, called directly (no HTTP) as in the reference's
explorer.rs:322-593 tests, plus one live HTTP round trip."""

import json
import threading
import urllib.request

import pytest

from stateright_tpu.explorer.server import (
    Snapshot,
    make_server,
    state_views,
    status_view,
)
from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.fixtures import BinaryClock
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def _checker(model):
    return model.checker().spawn_on_demand()


def test_can_init():
    checker = _checker(BinaryClock())
    views, err = state_views(checker, "/")
    assert err is None
    assert len(views) == len(list(BinaryClock().init_states()))
    for v in views:
        assert "action" not in v
        assert "state" in v and "fingerprint" in v
        assert v["properties"]


def test_can_next():
    model = BinaryClock()
    checker = _checker(model)
    init = list(model.init_states())[0]
    fp = fingerprint(init)
    views, err = state_views(checker, f"/{fp}")
    assert err is None
    assert len(views) >= 1
    for v in views:
        assert "action" in v
        assert "fingerprint" in v  # BinaryClock never ignores actions
        # The replayed successor matches the model's real transition.
        assert v["state"] in {repr(s) for s in model.next_states(init)}


def test_bad_fingerprints_404():
    checker = _checker(BinaryClock())
    views, err = state_views(checker, "/one/two")
    assert views is None and "Unable to parse" in err
    views, err = state_views(checker, "/12345678")
    assert views is None and "Unable to find state" in err


def test_smoke_status():
    checker = _checker(BinaryClock())
    s = status_view(checker)
    assert s["model"] == "BinaryClock"
    assert s["done"] is False
    assert [p[0] for p in s["properties"]] == ["Always", "Sometimes"]
    checker.run_to_completion()
    s = status_view(checker)
    assert s["done"] is True
    # "always in bounds" holds (no counterexample); "sometimes can be
    # zero" has an example path.
    assert s["properties"][0][2] is None
    assert s["properties"][1][2]


def test_browsing_steers_on_demand_search():
    """check_fingerprint pulls browsed states into the search
    (explorer.rs:255, 288 → on_demand.rs:139-159)."""
    model = TwoPhaseSys(rm_count=2)
    checker = _checker(model)
    before = checker.unique_state_count()
    views, err = state_views(checker, "/")
    assert err is None
    fp = views[0]["fingerprint"]
    state_views(checker, f"/{fp}")
    assert checker.unique_state_count() > before


def test_discovery_encoded_in_properties():
    model = TwoPhaseSys(rm_count=2)
    checker = _checker(model)
    checker.run_to_completion()
    props = {p[1]: p for p in status_view(checker)["properties"]}
    # sometimes-properties have examples; paths are non-empty.
    assert props["commit agreement"][2]
    assert all(part.isdigit() for part in props["commit agreement"][2].split("/"))


def test_http_round_trip():
    model = TwoPhaseSys(rm_count=2)
    checker = _checker(model)
    server = make_server(checker, Snapshot(), "127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/.status") as r:
            status = json.loads(r.read())
        assert status["model"] == "TwoPhaseSys"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/.states/") as r:
            views = json.loads(r.read())
        assert views and "fingerprint" in views[0]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/.runtocompletion", method="POST"
        )
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/.status") as r:
            assert json.loads(r.read())["done"] is True
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            assert b"Explorer" in r.read()
    finally:
        server.shutdown()


def test_request_telemetry_spans():
    """Round-14 metering brick (ROADMAP direction 4): every Explorer
    request handler runs inside an ``explorer_request`` span — one
    span event per request with the per-request wall and the
    cache-hit state (whether the request stayed inside the already-
    explored space or pulled new states into the on-demand search).
    Untraced serving pays only the shared no-op span."""
    from stateright_tpu.telemetry import RunTracer, validate_events

    model = TwoPhaseSys(rm_count=2)
    checker = _checker(model)
    server = make_server(checker, Snapshot(), "127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    tr = RunTracer()
    try:
        with tr.activate():
            tr.begin_run(lane=dict(engine="explorer"))
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.status"
            ) as r:
                json.loads(r.read())
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.states/"
            ) as r:
                views = json.loads(r.read())
            fp = views[0]["fingerprint"]
            # first browse of this fp explores (cache miss)...
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.states/{fp}"
            ) as r:
                json.loads(r.read())
            # ...the same browse again is served from explored space
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.states/{fp}"
            ) as r:
                json.loads(r.read())
            tr.end_run()
    finally:
        server.shutdown()
    validate_events(tr.events)
    spans = [e for e in tr.events
             if e["ev"] == "span" and e["phase"] == "explorer_request"]
    assert len(spans) == 4
    assert all(s["dur"] >= 0 and s["method"] == "GET" for s in spans)
    by_path = {}
    for s in spans:
        by_path.setdefault(s["path"], []).append(s)
    assert by_path["/.status"][0]["kind"] == "status"
    assert by_path["/.status"][0]["cache_hit"] is True
    browse = by_path[f"/.states/{fp}"]
    assert [s["cache_hit"] for s in browse] == [False, True]
    assert all("states" in s for s in browse)


def test_actor_model_svg_in_state_views():
    """ActorModel renders sequence-diagram SVG into Explorer views
    (model.rs:476-640 counterpart)."""
    from stateright_tpu.models.ping_pong import PingPongCfg, ping_pong_model

    model = ping_pong_model(PingPongCfg(max_nat=1))
    checker = model.checker().spawn_on_demand()
    views, err = state_views(checker, "/")
    assert err is None
    assert all(v.get("svg", "").startswith("<svg") for v in views)
    fp = views[0]["fingerprint"]
    views, err = state_views(checker, f"/{fp}")
    assert err is None
    delivered = [v for v in views if "fingerprint" in v]
    assert delivered and all("svg" in v for v in delivered)
    assert any("marker-end" in v["svg"] for v in delivered)
