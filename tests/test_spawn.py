"""The UDP actor runtime: the same actor classes the checker verified,
executed over real loopback sockets (spawn.rs:64-224 counterpart).

Ports are picked per-test from the ephemeral range to avoid clashes.
"""

import json
import socket
import time

import pytest

from stateright_tpu.actor import Id
from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
from stateright_tpu.actor.spawn import (
    json_serde,
    register_msg_types,
    spawn,
    spawn_paxos_cluster,
)
from stateright_tpu.models.ping_pong import Ping, PingPongActor, Pong


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _await(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_json_serde_round_trip():
    serialize, deserialize = json_serde(register_msg_types())
    from stateright_tpu.models.paxos import Prepared
    from stateright_tpu.actor.register import Internal

    for msg in [
        Put(1, "X"),
        Get(2),
        PutOk(1),
        GetOk(2, "X"),
        Internal(Prepared((1, Id(0)), ((1, Id(0)), (3, Id(3), "A")))),
    ]:
        out = deserialize(serialize(msg))
        assert out == msg or (
            # Ids decode as plain ints — structurally equal.
            json.loads(serialize(out)) == json.loads(serialize(msg))
        )


def test_ping_pong_over_udp():
    """The model-checked PingPongActor volleys over real sockets."""
    p0, p1 = _free_ports(2)
    id0 = Id.from_addr("127.0.0.1", p0)
    id1 = Id.from_addr("127.0.0.1", p1)
    serialize, deserialize = json_serde([Ping, Pong])
    handles = spawn(
        serialize,
        deserialize,
        [(id0, PingPongActor(serve_to=id1)), (id1, PingPongActor(None))],
    )
    try:
        assert _await(lambda: all(h.state and h.state >= 5 for h in handles))
    finally:
        for h in handles:
            h.stop()
        for h in handles:
            h.join(2)


def test_paxos_cluster_put_get_round_trip():
    """3 real PaxosActor servers decide a value and serve reads —
    driven by a raw UDP client, like the reference's `nc` workflow
    (examples/paxos.rs:403-419)."""
    base = _free_ports(4)
    # The cluster helper requires 3 consecutive ports; find a run.
    for attempt in range(20):
        probe = _free_ports(1)[0]
        try:
            handles = spawn_paxos_cluster(base_port=probe, block=False)
            break
        except OSError:
            continue
    else:
        pytest.skip("no 3 consecutive free ports")
    serialize, deserialize = json_serde(register_msg_types())
    client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    client.bind(("127.0.0.1", 0))
    client.settimeout(8.0)
    try:
        client.sendto(serialize(Put(42, "X")), ("127.0.0.1", probe))
        data, _ = client.recvfrom(65507)
        reply = deserialize(data)
        assert reply == PutOk(42), reply
        client.sendto(serialize(Get(43)), ("127.0.0.1", probe))
        data, _ = client.recvfrom(65507)
        reply = deserialize(data)
        assert reply == GetOk(43, "X"), reply
    finally:
        client.close()
        for h in handles:
            h.stop()
        for h in handles:
            h.join(2)
