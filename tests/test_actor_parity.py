"""Actor-layer parity: ordered reliable link, heterogeneous actors
(Choice / scripted clients), and the write-once-register adapter.

References: ordered_reliable_link.rs:32-207, actor.rs:343-549,
write_once_register.rs:16-331.
"""

from dataclasses import dataclass
from typing import Any

import pytest

from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Cow,
    Id,
    Network,
    Out,
)
from stateright_tpu.actor.choice import Choice, L, R, ScriptedActor
from stateright_tpu.actor.ordered_reliable_link import (
    Ack,
    Deliver,
    LinkState,
    NetworkTimer,
    OrderedReliableLink,
)
from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
from stateright_tpu.actor.write_once_register import (
    PutFail,
    WORegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.model import Expectation
from stateright_tpu.models.single_copy_register import SingleCopyActor
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister


# -- ordered reliable link ----------------------------------------------


class Sender(Actor):
    """Sends the values 42, 43 at startup (through the link wrapper) —
    the reference's ORL test fixture (ordered_reliable_link.rs:222-239)."""

    def on_start(self, id: Id, out: Out) -> tuple:
        out.send(Id(1), 42)
        out.send(Id(1), 43)
        return ()


class Receiver(Actor):
    """Records every delivered value in order."""

    def on_start(self, id: Id, out: Out) -> tuple:
        return ()

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        state.set(state.value + (msg,))


def orl_model() -> ActorModel:
    """Mirror of the reference's ORL model (ordered_reliable_link.rs:
    252-281): lossy duplicating network, boundary |network| < 4."""
    model = ActorModel()
    model.actor(OrderedReliableLink(Sender()))
    model.actor(OrderedReliableLink(Receiver()))
    model.init_network(Network.new_unordered_duplicating())
    model.set_lossy_network(True)

    def received(state) -> tuple:
        return state.actor_states[1].wrapped_state

    model.property(
        Expectation.ALWAYS,
        "no redelivery",
        lambda m, s: received(s).count(42) < 2 and received(s).count(43) < 2,
    )
    model.property(
        Expectation.ALWAYS,
        "ordered",
        lambda m, s: list(received(s)) == sorted(received(s)),
    )
    model.property(
        Expectation.SOMETIMES,
        "delivered",
        lambda m, s: received(s) == (42, 43),
    )
    model.within_boundary_fn(lambda cfg, s: len(s.network) < 4)
    return model


def test_orl_no_redelivery_and_ordered_over_lossy_duplicating():
    """The reference ORL guarantee (ordered_reliable_link.rs:283-300):
    at-most-once delivery in non-decreasing order, with full delivery
    reachable, over a lossy duplicating network with resends."""
    checker = orl_model().checker().spawn_bfs().join()
    checker.assert_no_discovery("no redelivery")
    checker.assert_no_discovery("ordered")
    checker.assert_any_discovery("delivered")


def test_orl_resend_timer_repopulates_lost_messages():
    """After a Drop, firing the network timer restores the envelope."""
    model = orl_model()
    init = list(model.init_states())[0]
    # Sender's pending-ack map holds both messages until acked.
    sender: LinkState = init.actor_states[0]
    assert sorted(sender.msgs_pending_ack.keys()) == [1, 2]
    assert sender.next_send_seq == 3


def test_orl_acks_clear_pending():
    model = orl_model()
    checker = model.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("delivered")
    final = path.last_state()
    assert final.actor_states[1].wrapped_state == (42, 43)


# -- heterogeneous actors (Choice / scripted) ----------------------------


def test_scripted_client_drives_server():
    """A ScriptedActor (actor.rs:515-549) drives a SingleCopyActor."""
    model = ActorModel()
    model.actor(SingleCopyActor())
    model.actor(
        ScriptedActor([(Id(0), Put(1, "X")), (Id(0), Get(2))])
    )
    model.init_network(Network.new_unordered_nonduplicating())
    model.property(
        Expectation.SOMETIMES,
        "read returns X",
        lambda m, s: any(
            isinstance(env.msg, GetOk) and env.msg.value == "X"
            for env in s.network.iter_deliverable()
        ),
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()


def test_choice_tags_states_disjointly():
    """Choice keeps two actor kinds' states type-disjoint
    (actor.rs:343-497)."""
    model = ActorModel()
    model.actor(Choice.left(SingleCopyActor()))
    model.actor(
        Choice.right_of(ScriptedActor([(Id(0), Put(1, "V"))]))
    )
    model.init_network(Network.new_unordered_nonduplicating())
    model.property(
        Expectation.SOMETIMES,
        "write acknowledged",
        lambda m, s: any(
            isinstance(env.msg, PutOk)
            for env in s.network.iter_deliverable()
        ),
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()
    init = list(model.init_states())[0]
    assert isinstance(init.actor_states[0], L)
    assert isinstance(init.actor_states[1], R)


# -- write-once register -------------------------------------------------


class WOServer(Actor):
    """Minimal write-once server: first Put wins, later Puts fail."""

    def on_start(self, id: Id, out: Out):
        return None  # unwritten

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        if isinstance(msg, Put):
            if state.value is None:
                state.set(msg.value)
                out.send(src, PutOk(msg.req_id))
            else:
                out.send(src, PutFail(msg.req_id))
        elif isinstance(msg, Get):
            out.send(src, GetOk(msg.req_id, state.value))


def wo_model() -> ActorModel:
    model = ActorModel(
        init_history=LinearizabilityTester(WORegister())
    )
    model.actor(WOServer())
    model.add_actors(
        WORegisterClient(put_count=1, server_count=1) for _ in range(2)
    )
    model.init_network(Network.new_unordered_nonduplicating())
    model.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda m, s: s.history.serialized_history() is not None,
    )
    model.property(
        Expectation.SOMETIMES,
        "a write fails",
        lambda m, s: any(
            isinstance(env.msg, PutFail)
            for env in s.network.iter_deliverable()
        ),
    )
    model.record_msg_in(record_returns)
    model.record_msg_out(record_invocations)
    return model


def test_wo_register_linearizable_and_second_write_fails():
    """Two clients racing to write a write-once register: histories
    stay linearizable against WORegister semantics, and some
    interleaving rejects the second write."""
    checker = wo_model().checker().spawn_bfs().join()
    checker.assert_properties()


def test_wo_register_counts_stable():
    c1 = wo_model().checker().spawn_bfs().join()
    c2 = wo_model().checker().spawn_dfs().join()
    assert c1.unique_state_count() == c2.unique_state_count()
    assert sorted(c1.discoveries()) == sorted(c2.discoveries())
