"""Device symmetry-reduction gate (``sym`` marker).

The tentpole contract (ops/canonical.py + the sort-merge engines):
candidates canonicalize to their orbit representative BEFORE the
fingerprint fold, so the visited key space is the reduced quotient
while the frontier keeps CONCRETE states — counterexample paths stay
replayable, exactly the host DFS split (dfs.rs:300-311). The gate
pins:

* kernel unit facts — spec validation refuses malformed layouts
  loudly; the canonicalization is bit-identical between the numpy
  host replay and the jax device path, idempotent, and constant on
  orbits (it matches ``representative_full`` through encode/decode);
* device-vs-host parity — the sort-merge engine under ``--symmetry``
  reproduces the host DFS symmetry oracle's count (80 at rm=3, 314
  at rm=5 — the PERFECT canonicalizer's order-independent counts;
  see symmetry.py on why the reference's 665 is a DFS-order
  artifact), same verdicts, replayable discovery paths;
* the reduction survives the machinery downstream of the fingerprint:
  tiered forced-spill, kill/resume (S=2 -> S=2 and the 2 -> 4
  re-shard route canonical keys), the sharded S=2 run itself;
* the ample-set enabled-bits filter preserves verdicts against the
  unfiltered oracle and REFUSES when the encoding declares no mask;
* the three former hand-rolled refusal messages are one helper
  (checkers/common.symmetry_refusal) — every refusing engine and the
  missing-capability device path speak the same words;
* a traced sym-vs-sym pair diffs to zero counter divergence, and the
  per-wave ``canonical_hits`` telemetry lane is live.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.two_phase_commit import (  # noqa: E402
    TwoPhaseSys,
)
from stateright_tpu.models.two_phase_commit_tpu import (  # noqa: E402
    TwoPhaseSysEncoded,
)
from stateright_tpu.ops.canonical import (  # noqa: E402
    DeviceRewriteSpec,
    MemberField,
    canonicalize_rows,
    validate_spec,
)

pytestmark = pytest.mark.sym


def _host_sym(rm):
    """The host DFS symmetry oracle: the PERFECT (full per-member
    tuple) canonicalizer, the one the device kernel implements."""
    return (
        TwoPhaseSys(rm_count=rm)
        .checker()
        .symmetry_fn(lambda s: s.representative_full())
        .spawn_dfs()
        .join()
    )


def _sym3(**kw):
    kw.setdefault("capacity", 1 << 10)
    kw.setdefault("frontier_capacity", 128)
    kw.setdefault("cand_capacity", 512)
    kw.setdefault("waves_per_sync", 2)
    return (
        TwoPhaseSys(rm_count=3)
        .checker()
        .symmetry()
        .spawn_tpu_sortmerge(**kw)
    )


# -- kernel unit facts -----------------------------------------------------


def test_validate_spec_refuses_malformed_layouts():
    f = MemberField(lane=0, shift=0, stride=2, width=2, sort_key=True)
    with pytest.raises(ValueError, match="singleton"):
        DeviceRewriteSpec(n_members=1, fields=(f,))
    with pytest.raises(ValueError, match="no member fields"):
        DeviceRewriteSpec(n_members=3, fields=())
    with pytest.raises(ValueError, match="overlap"):
        DeviceRewriteSpec(
            n_members=3,
            fields=(MemberField(0, 0, stride=1, width=2,
                                sort_key=True),),
        )
    with pytest.raises(ValueError, match="fit one uint32 lane"):
        DeviceRewriteSpec(
            n_members=8,
            fields=(MemberField(0, 8, stride=4, width=4,
                                sort_key=True),),
        )
    with pytest.raises(ValueError, match="no sort_key"):
        DeviceRewriteSpec(
            n_members=3,
            fields=(MemberField(0, 0, stride=2, width=2,
                                sort_key=False),),
        )
    with pytest.raises(ValueError, match="outside encoding width"):
        validate_spec(
            DeviceRewriteSpec(
                n_members=3,
                fields=(MemberField(5, 0, stride=2, width=2,
                                    sort_key=True),),
            ),
            width=2,
        )


def test_canonicalize_matches_representative_full_bit_identical():
    """Over EVERY reachable rm=4 state: the kernel (numpy host path
    AND jax device path, bit-identical to each other) equals
    encode(representative_full(decode(s))) — the device reduction is
    the host oracle's, limb for limb. Also idempotent."""
    import jax.numpy as jnp

    enc = TwoPhaseSysEncoded(4)
    spec = enc.device_rewrite_spec()
    model = TwoPhaseSys(rm_count=4)
    seen, queue = {}, list(model.init_states())
    while queue:
        s = queue.pop()
        k = tuple(enc.encode(s).tolist())
        if k in seen:
            continue
        seen[k] = s
        queue.extend(model.next_states(s))
    states = list(seen.values())
    assert len(states) == 1568  # the pinned rm=4 raw count
    rows = np.stack([enc.encode(s) for s in states])
    want = np.stack([
        enc.encode(s.representative_full()) for s in states
    ])
    got_np = canonicalize_rows(spec, rows, np)
    got_jnp = np.asarray(
        canonicalize_rows(spec, jnp.asarray(rows), jnp)
    )
    np.testing.assert_array_equal(got_np, want)
    np.testing.assert_array_equal(got_jnp, want)
    # idempotent: canonical forms are fixed points
    np.testing.assert_array_equal(
        canonicalize_rows(spec, got_np, np), got_np
    )


# -- device-vs-host parity -------------------------------------------------


def test_device_symmetry_rm3_matches_host_oracle():
    host = _host_sym(3)
    c = _sym3().join()
    assert c.unique_state_count() == host.unique_state_count() == 80
    assert sorted(c.discoveries()) == sorted(host.discoveries())
    # counterexample paths replay through CONCRETE states: the path
    # machinery never sees a canonical form it could not re-step
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state())


def test_device_symmetry_rm5_is_314_order_independent():
    """rm=5: 8,832 raw states reduce to 314 — the perfect
    canonicalizer's count, which is search-order-independent (the
    reference's pinned 665 is an artifact of its PARTIAL sort key
    meeting DFS expansion order; see symmetry.py)."""
    host = _host_sym(5)
    c = (
        TwoPhaseSys(rm_count=5)
        .checker()
        .symmetry()
        .spawn_tpu_sortmerge(
            capacity=1 << 11, frontier_capacity=256,
            cand_capacity=2048, waves_per_sync=4,
        )
        .join()
    )
    assert c.unique_state_count() == host.unique_state_count() == 314
    assert sorted(c.discoveries()) == sorted(host.discoveries())


# -- the reduction survives the downstream machinery -----------------------


def test_tiered_forced_spill_keeps_canonical_counts():
    """Canonical fingerprints survive the device-hot/host-cold spill:
    the tier layer dedups KEYS and never re-derives them, so a
    forced spill must not change the reduced count."""
    c = _sym3(capacity=1 << 10, tier_hot_rows=32).join()
    assert c.unique_state_count() == 80
    assert sorted(c.discoveries()) == sorted(
        _host_sym(3).discoveries()
    )


def test_sharded_s2_symmetry_parity():
    """S=2: ownership hashes the CANONICAL fingerprint, so whole
    orbits route to one shard and per-shard dedup IS global orbit
    dedup — same 80, same verdicts, replayable paths."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU mesh")
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .symmetry()
        .spawn_tpu_sharded_sortmerge(
            n_shards=2, capacity=1 << 10, frontier_capacity=128,
            cand_capacity=1024, bucket_capacity=512,
            waves_per_sync=2,
        )
        .join()
    )
    assert c.unique_state_count() == 80
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state())


def test_kill_resume_and_reshard_keep_canonical_counts(tmp_path):
    """Kill at a chunk boundary, resume — and resume onto a DIFFERENT
    shard count: the snapshot carries canonical fingerprints, and the
    (owner, fp) re-route hashes them again, so both resumes land on
    the oracle's 80."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device CPU mesh")
    from stateright_tpu import faultinject

    def spawn(n_shards, **kw):
        return (
            TwoPhaseSys(rm_count=3)
            .checker()
            .symmetry()
            .spawn_tpu_sharded_sortmerge(
                n_shards=n_shards, capacity=1 << 10,
                frontier_capacity=128, cand_capacity=1024,
                bucket_capacity=512, waves_per_sync=2, **kw,
            )
        )

    snap = str(tmp_path / "sym.ckpt")
    c = spawn(2, checkpoint_every=1, checkpoint_path=snap)
    c.max_fault_retries = 0
    faultinject.arm("raise", "chunk_boundary", 1)
    try:
        with pytest.raises(faultinject.InjectedFault):
            c.join()
    finally:
        faultinject.disarm_all()

    same = spawn(2)
    same.resume_from(snap)
    same.join()
    assert same.unique_state_count() == 80

    re4 = spawn(4)
    re4.resume_from(snap)
    re4.join()
    assert re4.unique_state_count() == 80


# -- the ample-set enabled-bits filter -------------------------------------


def test_ample_set_preserves_verdicts():
    """The 2pc ample mask (drop the redundant abort-choice slot for
    rm >= 1) explores fewer states but reaches the SAME verdicts as
    the unfiltered oracle — on its own and composed with symmetry."""
    full = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    amp = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=128,
            cand_capacity=512, waves_per_sync=2, ample_set=True,
        )
        .join()
    )
    assert amp.unique_state_count() == 260  # < full's 288
    assert full.unique_state_count() == 288
    assert sorted(amp.discoveries()) == sorted(full.discoveries())
    for name, path in amp.discoveries().items():
        prop = amp.model.property_by_name(name)
        assert prop.condition(amp.model, path.last_state())

    both = _sym3(ample_set=True).join()
    assert both.unique_state_count() == 76  # < sym-only's 80
    assert sorted(both.discoveries()) == sorted(full.discoveries())


def test_ample_set_refuses_without_encoding_mask():
    """No declared ample mask -> loud refusal at program build (the
    engine cannot invent a sound reduction), not a silent full run."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    c = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 12, frontier_capacity=512,
            cand_capacity=2048, ample_set=True,
        )
    )
    with pytest.raises(ValueError, match="sound reduction"):
        c.join()


# -- one refusal voice -----------------------------------------------------


def test_refusal_messages_are_unified():
    """Every refusing engine raises checkers/common.symmetry_refusal's
    wording: the engine name, the supported list, and — on the device
    capability path — the missing capability by name."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    host_engines = (
        ("spawn_bfs", "spawn_bfs"),
        ("spawn_on_demand", "spawn_on_demand"),
        ("spawn_tpu", "spawn_tpu (hash engine)"),
    )
    for name, label in host_engines:
        b = TwoPhaseSys(rm_count=3).checker().symmetry()
        with pytest.raises(ValueError) as ei:
            getattr(b, name)()
        msg = str(ei.value)
        assert f"symmetry reduction: {label} cannot honor it" in msg
        assert "spawn_dfs / spawn_simulation" in msg
        assert "device_rewrite_spec()" in msg

    # the sort-merge engine CAN honor it — but only for encodings
    # that declare the capability; paxos does not
    b = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .symmetry()
    )
    with pytest.raises(ValueError) as ei:
        b.spawn_tpu_sortmerge(capacity=1 << 12)
    msg = str(ei.value)
    assert "spawn_tpu_sortmerge cannot honor it" in msg
    assert "missing capability" in msg


# -- telemetry: the canonical_hits lane + traced A/B zero divergence ------


def test_traced_sym_pair_diffs_clean_and_logs_canonical_hits(tmp_path):
    from stateright_tpu.telemetry import (
        RunTracer,
        diff_traces,
        load_trace,
        write_artifacts,
    )

    def traced(name):
        tr = RunTracer()
        with tr.activate():
            c = _sym3(waves_per_sync=4).join()
        assert c.unique_state_count() == 80
        jsonl, _ = write_artifacts(tr, root=str(tmp_path))
        return jsonl

    a = load_trace(traced("a"))
    b = load_trace(traced("b"))
    rep = diff_traces(a, b)
    assert rep["ok"], rep["divergences"]
    assert not rep["divergences"]
    # the optional lane is LIVE on a symmetry run: some wave merged
    # candidates whose canonical form differed from the raw state
    waves = [e for e in a if e["ev"] == "wave"]
    assert waves, "no wave events in the traced run"
    hits = sum(int(w.get("canonical_hits") or 0) for w in waves)
    assert hits > 0, waves
