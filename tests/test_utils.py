"""Utility collections: hashable set/map, DenseNatMap, VectorClock."""

import pytest

from stateright_tpu import (
    DenseNatMap,
    HashableMap,
    HashableSet,
    VectorClock,
    stable_hash,
)


def test_hashable_set_order_independent_digest():
    a = HashableSet([1, 2, 3])
    b = HashableSet([3, 2, 1])
    assert a == b
    assert stable_hash(a) == stable_hash(b)
    assert hash(a) == hash(b)


def test_hashable_set_immutability():
    a = HashableSet([1])
    b = a.add(2)
    assert 2 not in a and 2 in b
    assert a.add(1) is a
    assert b.remove(2) == a


def test_hashable_map_digest_and_updates():
    a = HashableMap({"x": 1, "y": 2})
    b = HashableMap({"y": 2, "x": 1})
    assert a == b and stable_hash(a) == stable_hash(b)
    c = a.set("z", 3)
    assert "z" not in a and c["z"] == 3
    assert c.remove("z") == a
    assert a.set("x", 1) is a


def test_dense_nat_map():
    m = DenseNatMap([10, 20])
    assert m[0] == 10 and m[1] == 20
    m2 = m.set(2, 30)  # append at end: dense
    assert len(m2) == 3 and m2[2] == 30
    m3 = m2.set(0, 99)
    assert m3[0] == 99 and m2[0] == 10
    with pytest.raises(IndexError):
        m.set(5, 1)  # gap insert (densenatmap.rs:98-113)


def test_vector_clock_ordering():
    a = VectorClock().incremented(0)  # [1]
    b = a.incremented(1)  # [1,1]
    assert a < b and a <= b and not (b <= a)
    c = VectorClock().incremented(1)  # [0,1]
    assert a.partial_cmp(c) is None  # concurrent
    assert a.merge_max(c) == VectorClock([1, 1])


def test_vector_clock_trailing_zeros_ignored():
    assert VectorClock([1, 0, 0]) == VectorClock([1])
    assert stable_hash(VectorClock([1, 0])) == stable_hash(VectorClock([1]))
    assert VectorClock([1]).get(5) == 0
