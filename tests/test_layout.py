"""The transposed [W, N] resident layout (round 9, PERF.md §layout).

Two halves:

* **transpose-boundary round-trips** — the transposed invocation
  adapters (encoding.py ``*_cols``, ops/fingerprint.py
  ``fingerprint_u32v_t``) must be BIT-identical to the row-major
  contract views on real encoded states, at the shapes the bench
  lanes run (paxos 2c/3s: W=13 multi-word masks; 2pc rm=4 and the
  rm=7 width class: W=2, L=1 scalar-word lane). Any divergence here
  means the engines' [W, N] path explores a different space than the
  row-major contract the encodings are pinned by.
* **count parity** — the transposed engine reproduces the pinned
  counts end-to-end: paxos 2c/3s = 16,668 and 2pc rm=7 = 296,448
  (the rm=4 space rides tier-1 via test_sortmerge's sparse-vs-dense
  parity), with discovery sets intact.

Marked ``layout``; rides tier-1's ``-m 'not slow'`` run.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.encoding import (  # noqa: E402
    enabled_bits_cols,
    enabled_mask_cols,
    property_conditions_cols,
    step_slot_cols_fn,
    within_boundary_cols,
)
from stateright_tpu.ops.fingerprint import (  # noqa: E402
    fingerprint_u32v,
    fingerprint_u32v_t,
)

pytestmark = pytest.mark.layout


def _bfs_prefix_vecs(enc, limit=256):
    """Real encoded states: init vecs + a host-BFS prefix, so the
    adapter round-trips run on reachable field values, not random
    bit patterns."""
    from collections import deque

    m = enc.host_model
    seen = {}
    q = deque(m.init_states())
    for s in list(q):
        seen[tuple(enc.encode(s).tolist())] = True
    while q and len(seen) < limit:
        s = q.popleft()
        for t in m.next_states(s):
            k = tuple(enc.encode(t).tolist())
            if k not in seen:
                seen[k] = True
                q.append(t)
    return jnp.asarray(np.array(sorted(seen), dtype=np.uint32))


def _encodings():
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.paxos_tpu import PaxosEncoded
    from stateright_tpu.models.two_phase_commit_tpu import (
        TwoPhaseSysEncoded,
    )

    return [
        PaxosEncoded(PaxosModelCfg(client_count=2, server_count=3)),
        TwoPhaseSysEncoded(4),
        # the rm=7 bench-lane width class (same W=2/L=1 layout at a
        # wider slot range)
        TwoPhaseSysEncoded(7),
    ]


def test_fingerprint_fold_transposed_bit_identical():
    """fingerprint_u32v_t(x.T) == fingerprint_u32v(x), on numpy AND
    under jit, across widths including the engines' real W."""
    rng = np.random.default_rng(11)
    for w in (1, 2, 13, 19, 32):
        x = rng.integers(0, 2**32, size=(257, w), dtype=np.uint32)
        lo_r, hi_r = fingerprint_u32v(x, np)
        lo_t, hi_t = fingerprint_u32v_t(x.T, np)
        assert (lo_r == lo_t).all() and (hi_r == hi_t).all()
        lo_j, hi_j = jax.jit(
            lambda v: fingerprint_u32v_t(v, jnp)
        )(jnp.asarray(x.T))
        assert (np.asarray(lo_j) == lo_r).all()
        assert (np.asarray(hi_j) == hi_r).all()
    # the transposed fold traces gather-free (it is row slices)
    jx = jax.make_jaxpr(lambda v: fingerprint_u32v_t(v, jnp))(
        jnp.zeros((13, 64), jnp.uint32)
    )
    assert not any(
        "gather" in e.primitive.name for e in jx.jaxpr.eqns
    )


def test_transposed_adapters_round_trip():
    """Every transposed adapter equals its row-major contract view on
    real reachable states: bits, mask, properties, boundary, and the
    step over every enabled (row, slot) pair."""
    for enc in _encodings():
        vecs = _bfs_prefix_vecs(enc)
        vecs_t = vecs.T
        bits_r = np.asarray(
            jax.jit(jax.vmap(enc.enabled_bits_vec))(vecs)
        )
        bits_t = np.asarray(
            jax.jit(lambda v, e=enc: enabled_bits_cols(e, v))(vecs_t)
        )
        assert (bits_r == bits_t).all(), type(enc).__name__
        mask_r = np.asarray(
            jax.jit(jax.vmap(enc.enabled_mask_vec))(vecs)
        )
        mask_t = np.asarray(
            jax.jit(lambda v, e=enc: enabled_mask_cols(e, v))(vecs_t)
        )
        assert (mask_r == mask_t).all(), type(enc).__name__
        props_r = np.asarray(
            jax.jit(jax.vmap(enc.property_conditions_vec))(vecs)
        )
        props_t = np.asarray(
            jax.jit(
                lambda v, e=enc: property_conditions_cols(e, v)
            )(vecs_t)
        )
        assert (props_r == props_t).all(), type(enc).__name__
        wb_r = np.asarray(
            jax.jit(jax.vmap(enc.within_boundary_vec))(vecs)
        )
        wb_t = np.asarray(
            jax.jit(lambda v, e=enc: within_boundary_cols(e, v))(
                vecs_t
            )
        )
        assert wb_t.shape in ((), (vecs.shape[0],))
        # value equality too, not just shape — a trivial boundary may
        # come back as a broadcastable scalar on either view
        n = vecs.shape[0]
        assert (
            np.broadcast_to(wb_r, (n,)) == np.broadcast_to(wb_t, (n,))
        ).all(), type(enc).__name__

        rows, slots = np.nonzero(mask_r)
        step_r = np.asarray(
            jax.jit(jax.vmap(enc.step_slot_vec))(
                vecs[jnp.asarray(rows)],
                jnp.asarray(slots.astype(np.uint32)),
            )
        )
        succ_t, _, _ = jax.jit(step_slot_cols_fn(enc))(
            vecs[jnp.asarray(rows)],
            jnp.asarray(slots.astype(np.uint32)),
        )
        succ_t = np.asarray(succ_t)
        assert succ_t.shape == (enc.width, rows.shape[0])
        assert (succ_t.T == step_r).all(), type(enc).__name__
        # and the transposed fold agrees on the successors
        lo_r, hi_r = fingerprint_u32v(step_r, np)
        lo_t, hi_t = fingerprint_u32v_t(succ_t, np)
        assert (lo_r == lo_t).all() and (hi_r == hi_t).all()


def test_layout_count_parity_paxos_2c3s():
    """The transposed engine reproduces the pinned paxos 2c/3s count
    (16,668) with the host discovery set, paths on (exercises the
    derived-children parent log end to end)."""
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    sm = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 15,
            frontier_capacity=1 << 12,
            cand_capacity=1 << 14,
        )
        .join()
    )
    assert sm.unique_state_count() == 16668
    assert sorted(sm.discoveries()) == ["value chosen"]
    for name, path in sm.discoveries().items():
        prop = sm.model.property_by_name(name)
        assert prop.condition(sm.model, path.last_state())


def test_layout_count_parity_2pc_rm7():
    """The transposed engine reproduces the pinned 2pc rm=7 bench-lane
    count (296,448) — the largest CPU-feasible lane, exercising the
    production compaction branches at real ladder depth."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    sm = (
        TwoPhaseSys(rm_count=7)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 19,
            frontier_capacity=1 << 16,
            cand_capacity=1 << 19,
            track_paths=False,
        )
        .join()
    )
    assert sm.unique_state_count() == 296448
    sm.assert_properties()
    assert sm.discovered_property_names() == {
        "abort agreement", "commit agreement",
    }
