"""The two reference examples with no prior counterpart (VERDICT r2
missing #6): timers.rs (dedicated timer semantics, incl. the
no-op-with-timer pruning) and interaction.rs (user-input modeling
with a depth-bounded loosely-bounded space)."""

from stateright_tpu.actor import Network
from stateright_tpu.actor.compile import compile_actor_model
from stateright_tpu.models.interaction import InputState, interaction_model
from stateright_tpu.models.timers import (
    PingerModelCfg,
    PingerState,
    pinger_model,
)


def test_timers_noop_timer_pruned():
    """The NoOp timer only re-arms itself — is_no_op_with_timer prunes
    it, so the timer never produces a transition (actor.rs:254-264)."""
    model = pinger_model(PingerModelCfg(server_count=2))
    [init] = model.init_states()
    from stateright_tpu.actor.model import Timeout
    from stateright_tpu.actor import Id

    assert model.next_state(init, Timeout(Id(0), "NoOp")) is None
    # Even/Odd timers DO fire transitions (they send pings).
    assert model.next_state(init, Timeout(Id(0), "Odd")) is not None


def test_timers_bounded_check_bfs_dfs_agree():
    m1 = pinger_model(PingerModelCfg(server_count=3))
    c1 = m1.checker().target_max_depth(4).spawn_bfs().join()
    assert c1.unique_state_count() > 1
    c1.assert_properties()  # the always-"true" invariant holds
    # Timers survive through the compiled TPU encoding too: the timer
    # universe and the no-op-with-timer pruning compile into timeout
    # slots (zero hand-written device code).
    m2 = pinger_model(PingerModelCfg(server_count=3))
    enc = compile_actor_model(
        m2,
        properties={"true": lambda ctx, jnp: jnp.bool_(True)},
        closure_actor_bound=lambda i, s: s.sent + s.received <= 4,
    )
    m3 = pinger_model(PingerModelCfg(server_count=3))
    host = m3.checker().target_max_depth(3).spawn_bfs().join()
    tpu = (
        m2.checker()
        .target_max_depth(3)
        .spawn_tpu_sortmerge(
            encoded=enc,
            capacity=1 << 12,
            frontier_capacity=1 << 10,
            cand_capacity=1 << 12,
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()


def test_interaction_success_example_found():
    """interaction.rs: the eventually 'success' property is satisfiable
    within the depth bound; BFS finds no counterexample and the state
    space is non-trivial."""
    checker = (
        interaction_model().checker().target_max_depth(12).spawn_bfs().join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() > 10


def test_interaction_reaches_success_state():
    """Breadth-first probe: the success path (input timer → increment →
    query timer → report → reply ≥ threshold) is ~6 levels deep."""
    from collections import deque

    model = interaction_model()
    seen_success = False
    frontier = deque(model.init_states())
    visited = set()
    while frontier and not seen_success and len(visited) < 5000:
        state = frontier.popleft()
        for action in model.actions(state):
            ns = model.next_state(state, action)
            if ns is None or ns in visited:
                continue
            visited.add(ns)
            if any(
                isinstance(a, InputState) and a.success
                for a in ns.actor_states
            ):
                seen_success = True
            frontier.append(ns)
    assert seen_success
