"""Stable fingerprinting: determinism, type separation, order independence."""

import subprocess
import sys
from dataclasses import dataclass
from enum import Enum

from stateright_tpu import fingerprint, stable_hash


def test_deterministic_across_processes():
    # The whole point (reference src/lib.rs:357-375): digests must be
    # stable across runs so state counts and encoded paths reproduce.
    code = (
        "from stateright_tpu import stable_hash;"
        "print(stable_hash(('abc', 42, frozenset([1, 2, 3]))))"
    )
    out1 = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, check=True
    ).stdout.strip()
    assert out1 == str(stable_hash(("abc", 42, frozenset([1, 2, 3]))))


def test_type_separation():
    values = [1, "1", (1,), [1], frozenset([1]), {1: 1}, 1.0, b"1", True, None]
    digests = [stable_hash(v) for v in values]
    assert len(set(digests)) == len(digests)


def test_int_edge_cases():
    vals = [0, 1, -1, 2**63, 2**64 - 1, 2**64, -(2**64), 2**130, -(2**130)]
    digests = [stable_hash(v) for v in vals]
    assert len(set(digests)) == len(digests)


def test_unordered_collections_order_independent():
    assert stable_hash(frozenset([1, 2, 3])) == stable_hash(frozenset([3, 1, 2]))
    assert stable_hash({"a": 1, "b": 2}) == stable_hash({"b": 2, "a": 1})
    # set == frozenset with same elements
    assert stable_hash({1, 2}) == stable_hash(frozenset([2, 1]))


def test_ordered_collections_order_dependent():
    assert stable_hash((1, 2)) != stable_hash((2, 1))
    assert stable_hash([1, 2]) != stable_hash([2, 1])


def test_dataclass_and_enum():
    @dataclass(frozen=True)
    class P:
        x: int
        y: int

    class Color(Enum):
        RED = 1
        BLUE = 2

    assert stable_hash(P(1, 2)) == stable_hash(P(1, 2))
    assert stable_hash(P(1, 2)) != stable_hash(P(2, 1))
    assert stable_hash(Color.RED) != stable_hash(Color.BLUE)


def test_fingerprint_nonzero():
    for v in range(200):
        assert fingerprint((v, v + 1)) != 0


def test_numpy_arrays():
    import numpy as np

    a = np.arange(8, dtype=np.uint32)
    b = np.arange(8, dtype=np.uint32)
    assert stable_hash(a) == stable_hash(b)
    assert stable_hash(a) != stable_hash(a.astype(np.int64))
