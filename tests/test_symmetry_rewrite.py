"""Recursive id rewriting for generic actor symmetry (VERDICT r2 #7).

The round-2 ``actor_state_representative`` rewrote only envelope
src/dst; ids INSIDE message payloads, actor states, and history stayed
stale, silently collapsing distinct states for any protocol whose
messages carry ids — reproduced here by the claim protocol, then shown
fixed: symmetry verdicts match the unsymmetrized run (reference
rewrite.rs:146-163, network.rs:311-324 semantics).
"""

import pytest

from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.model import Expectation
from stateright_tpu.symmetry import (
    RewritePlan,
    actor_state_representative,
    rewrite_value,
)


class Claimer(Actor):
    """Each actor broadcasts ('claim', own_id); state = ids seen.

    Both the message payload and the actor state embed Ids, so a
    representative that rewrites only envelope endpoints maps states in
    DIFFERENT orbits to the same key.
    """

    def __init__(self, n: int):
        self.n = n

    def on_start(self, id: Id, out: Out):
        for peer in range(self.n):
            if peer != int(id):
                out.send(Id(peer), ("claim", id))
        return frozenset()

    def on_msg(self, id: Id, state, src: Id, msg, out: Out) -> None:
        if isinstance(msg, tuple) and msg[0] == "claim":
            if msg[1] not in state.value:
                state.set(state.value | {msg[1]})


def claim_model(n: int) -> ActorModel:
    model = ActorModel()
    for _ in range(n):
        model.actor(Claimer(n))
    model.init_network(Network.new_unordered_nonduplicating())
    model.property(
        Expectation.SOMETIMES,
        "someone saw everyone",
        lambda m, s: any(len(a) == n - 1 for a in s.actor_states),
    )
    model.property(
        Expectation.ALWAYS,
        "never sees self",
        lambda m, s: all(
            Id(i) not in a for i, a in enumerate(s.actor_states)
        ),
    )
    return model


def test_rewrite_value_recurses_into_payloads_and_containers():
    plan = RewritePlan([2, 0, 1])  # old->new: 0->1, 1->2, 2->0
    assert rewrite_value(Id(0), plan) == Id(1)
    assert rewrite_value(("claim", Id(2)), plan) == ("claim", Id(0))
    assert rewrite_value(frozenset({Id(0), Id(1)}), plan) == frozenset(
        {Id(1), Id(2)}
    )
    assert rewrite_value({Id(1): "x"}, plan) == {Id(2): "x"}
    # Plain data passes through untouched.
    assert rewrite_value(("data", 7, "s"), plan) == ("data", 7, "s")


def test_rewrite_value_refuses_unknown_types():
    class Opaque:
        pass

    with pytest.raises(TypeError, match="rewrite actor ids"):
        rewrite_value(Opaque(), RewritePlan([0]))


def _apply_permutation(state, perm):
    """π(state) for an old→new actor permutation: reindex the per-actor
    tuples and rewrite every embedded id — ground truth for orbits."""
    from dataclasses import replace

    from stateright_tpu.actor.network import (
        Envelope,
        UnorderedNonDuplicating,
    )

    # RewritePlan wants new-position → old-index; invert the mapping.
    inv = [0] * len(perm)
    for old, new in enumerate(perm):
        inv[new] = old
    plan = RewritePlan(inv)
    net = UnorderedNonDuplicating(
        {
            Envelope(
                rewrite_value(e.src, plan),
                rewrite_value(e.dst, plan),
                rewrite_value(e.msg, plan),
            ): c
            for e, c in state.network.counts.items()
        }
    )
    return replace(
        state,
        actor_states=tuple(
            rewrite_value(s, plan)
            for s in plan.reindex(state.actor_states)
        ),
        timers_set=tuple(plan.reindex(state.timers_set)),
        crashed=tuple(plan.reindex(state.crashed)),
        network=net,
    )


def test_representative_stays_in_orbit():
    """THE soundness invariant (and the round-2 regression): the
    representative must be a genuine member of the state's symmetry
    orbit. The envelope-only rewrite produced hybrids — actor states
    re-sorted but payload/state ids stale — that lie OUTSIDE the orbit,
    collapsing states from different orbits (silent under-exploration,
    the most dangerous checker failure mode)."""
    from itertools import permutations

    from stateright_tpu.actor.model import Deliver

    model = claim_model(3)
    [init] = model.init_states()
    s1 = model.next_state(init, Deliver(Id(1), Id(0), ("claim", Id(1))))
    s2 = model.next_state(init, Deliver(Id(2), Id(0), ("claim", Id(2))))
    assert s1 != s2
    for s in (init, s1, s2):
        orbit = {_apply_permutation(s, perm)
                 for perm in permutations(range(3))}
        assert actor_state_representative(s) in orbit
    # States whose orbits differ keep distinct representatives.
    assert actor_state_representative(init) != actor_state_representative(
        s1
    )
    # s1 and s2 are in the SAME orbit (swap actors 1 and 2 carries one
    # to the other, payloads included).
    assert s2 in {
        _apply_permutation(s1, perm) for perm in permutations(range(3))
    }


def test_symmetry_matches_unsymmetrized_verdicts():
    host = claim_model(3).checker().spawn_dfs().join()
    sym = (
        claim_model(3)
        .checker()
        .symmetry_fn(actor_state_representative)
        .spawn_dfs()
        .join()
    )
    assert sorted(sym.discoveries()) == sorted(host.discoveries())
    sym.assert_properties()
    host.assert_properties()
    # Symmetry visits no more states, and at least the orbit count.
    assert sym.unique_state_count() <= host.unique_state_count()
