"""Memory-observability gate (``pytest -m mem``).

Covers the round-12 tentpole surface end to end on CPU:

* the resident-buffer ledger — ``memory_plan`` totals match the
  ACTUAL device arrays' ``nbytes`` on real engine buffers, for both
  sort-merge engines AND the hash engine, single-chip and sharded
  (per-shard bytes checked against the arrays' addressable shards);
* event schema — memory_plan/memory_watermark validate, chunk events
  carry the polled ``mem_bytes`` lane, untraced runs emit nothing
  (but still expose ``checker.memory_plan``) and keep identical
  counts;
* the ``engine_mode`` satellite — the CHUNKED memory-lean flip lands
  as a telemetry event on the forced flip, with counts unchanged;
* occupancy warnings priced in bytes (the shared formatter, at both
  the hash-engine probe-pressure call site and shard_balance);
* tools/mem_report.py — report rendering, ``--json`` MEM_r* artifact
  numbering (own sequence, through artifacts.py), exit 2 on traces
  without memory events;
* trace_diff memory alignment — plan shapes exact (divergence fails
  the gate), measured temp/live bytes under ``--threshold``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu import memplan, telemetry  # noqa: E402
from stateright_tpu.telemetry import (  # noqa: E402
    RunTracer,
    diff_traces,
    format_diff,
    load_trace,
    memory_summary,
    validate_events,
)

pytestmark = pytest.mark.mem

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _twopc_builder(rm=3):
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    return TwoPhaseSys(rm_count=rm).checker()


def _traced_checker(spawn, **kw):
    tracer = RunTracer()
    with tracer.activate():
        checker = spawn(**kw)
        checker.keep_final_carry = True
        checker.join()
    return tracer, checker


def _check_plan_vs_nbytes(checker, n_shards=1):
    """THE acceptance contract: every resident ledger row matches the
    real device array the engine kept (shape, dtype, nbytes), and the
    totals add up."""
    plan = checker.memory_plan
    assert plan is not None
    carry = checker._final_carry
    assert set(e["name"] for e in plan["resident"]) == set(carry)
    total = 0
    for e in plan["resident"]:
        arr = carry[e["name"]]
        assert tuple(e["shape"]) == tuple(arr.shape), e["name"]
        assert e["dtype"] == str(np.dtype(arr.dtype)), e["name"]
        assert e["bytes"] == arr.nbytes, e["name"]
        total += arr.nbytes
        if n_shards > 1:
            shard_nbytes = arr.addressable_shards[0].data.nbytes
            assert e["per_shard_bytes"] == shard_nbytes, e["name"]
    assert plan["resident_bytes"] == total
    assert plan["n_shards"] == n_shards
    assert plan["total_bytes"] >= plan["resident_bytes"]
    assert plan["classes"], "per-ladder-class staging must exist"
    for c in plan["classes"]:
        assert c["staging_bytes"] == sum(
            s["bytes"] for s in c["staging"]
        )


# -- plan vs nbytes on real engine buffers (all four engines) ------------


def test_plan_matches_nbytes_sortmerge_single_chip():
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=True,
    )
    assert c.unique_state_count() == 288
    _check_plan_vs_nbytes(c)
    validate_events(tracer.events)


def test_plan_matches_nbytes_sortmerge_sharded():
    n = jax.device_count()
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sharded_sortmerge(
            **kw),
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=True,
    )
    assert c.unique_state_count() == 288
    _check_plan_vs_nbytes(c, n_shards=n)
    validate_events(tracer.events)
    # the sharded resident buffers really split: vkeys is the SoA
    # [2, S * C_pad] block, so per-shard is exactly 1/S of it
    vk = next(e for e in c.memory_plan["resident"]
              if e["name"] == "vkeys")
    assert vk["sharded"] and vk["per_shard_bytes"] * n == vk["bytes"]


def test_plan_matches_nbytes_hash_engines():
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu(**kw),
        capacity=1 << 12, frontier_capacity=256,
    )
    assert c.unique_state_count() == 288
    _check_plan_vs_nbytes(c)

    n = jax.device_count()
    tracer2, c2 = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sharded(**kw),
        capacity=1 << 10, frontier_capacity=256,
    )
    assert c2.unique_state_count() == 288
    _check_plan_vs_nbytes(c2, n_shards=n)


# -- event schema / polling ----------------------------------------------


def test_memory_events_schema_and_polling(tmp_path):
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=False,
    )
    validate_events(tracer.events)
    plans = [e for e in tracer.events if e["ev"] == "memory_plan"]
    assert len(plans) == 1
    plan = plans[0]
    assert plan["engine"] == "SortMergeTpuBfsChecker"
    # compiled-program analysis: reported on this backend (CPU XLA
    # answers memory_analysis) — or explicitly null, never missing
    assert "compiled" in plan
    wms = [e for e in tracer.events if e["ev"] == "memory_watermark"]
    assert len(wms) == 1
    wm = wms[0]
    # CPU: memory_stats() is None, so the live-array fallback polled
    assert wm["source"] == "live_arrays"
    assert wm["device_peak_bytes"] > 0
    assert wm["polls"] >= 1
    hr = wm["headroom"]
    assert hr["visited_rows"] == 288
    assert hr["visited_used_bytes"] == 288 * hr["bytes_per_row"]
    assert wm["projection"]["kind"] == "next_v_class"
    assert wm["projection"]["next_vkeys_bytes"] > 0
    # every chunk polled at the existing sync — no chunk without it
    chunks = [e for e in tracer.events if e["ev"] == "chunk"]
    assert chunks and all(
        isinstance(e.get("mem_bytes"), int) for e in chunks
    )
    # the peak is the max over the polls
    assert wm["device_peak_bytes"] == max(
        e["mem_bytes"] for e in chunks
    )
    # JSONL round-trip preserves the memory events
    path = tracer.write_jsonl(str(tmp_path / "t.jsonl"))
    evs = load_trace(path)
    validate_events(evs)
    summary = memory_summary(evs)
    assert summary is not None
    assert summary["plan"]["resident_bytes"] == plan["resident_bytes"]
    assert summary["chunk_mem"]
    # run peak lands in checker metrics too (bench embeds it)
    assert c.metrics["device_peak_bytes"] == wm["device_peak_bytes"]


def test_untraced_run_emits_nothing_but_keeps_plan():
    c = _twopc_builder().spawn_tpu_sortmerge(
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=False,
    ).join()
    assert c.unique_state_count() == 288
    # the ledger exists untraced (bench.py embeds it per lane) ...
    assert c.memory_plan is not None
    assert c.memory_plan["resident_bytes"] > 0
    # ... but no polling happened (no tracer: no watermark metric)
    assert "device_peak_bytes" not in c.metrics
    # untraced and traced explore identically (the smoke contract)
    tracer, c2 = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=False,
    )
    assert c2.unique_state_count() == c.unique_state_count()
    # the untraced plan has no wave-log lanes; the traced one does
    names = {e["name"] for e in c.memory_plan["resident"]}
    names2 = {e["name"] for e in c2.memory_plan["resident"]}
    assert "wlog" not in names and "wlog" in names2


def test_compiled_analysis_transient_failure_not_cached(tmp_path,
                                                        monkeypatch):
    """A FAILED lower/compile must not poison the persisted analysis
    cache — only a backend that genuinely can't report the analysis
    caches its None."""
    monkeypatch.setattr(
        memplan, "_analysis_store",
        lambda: str(tmp_path / "mem_analysis.json"),
    )
    memplan._ANALYSIS_CACHE.clear()

    class Broken:
        def lower(self, spec):
            raise RuntimeError("device busy")

    assert memplan.compiled_memory_analysis(Broken(), {}, "tok") is None
    assert "tok" not in str(memplan._ANALYSIS_CACHE)
    assert not os.path.exists(str(tmp_path / "mem_analysis.json"))
    # a working compile afterwards lands and persists
    f = jax.jit(lambda x: x + 1)
    spec = jax.ShapeDtypeStruct((4,), "uint32")
    result = memplan.compiled_memory_analysis(f, spec, "tok")
    assert result is not None
    assert os.path.exists(str(tmp_path / "mem_analysis.json"))
    memplan._ANALYSIS_CACHE.clear()


def test_validate_rejects_inconsistent_plan():
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane={})
        tr.event(
            "memory_plan", engine="X",
            resident=[dict(name="a", shape=[2, 4], dtype="uint32",
                           bytes=32)],
            resident_bytes=999,  # != 32
            classes=[], compiled=None, total_bytes=999,
        )
        tr.end_run()
    with pytest.raises(ValueError, match="resident_bytes"):
        validate_events(tr.events)


# -- the engine_mode satellite (CHUNKED memory-lean flip) ----------------


def test_engine_mode_event_fires_on_forced_chunked_flip():
    # Force the flip: a tiny flat budget makes every compaction class
    # exceed Ba * row_pad, so the sparse wave runs memory-lean.
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256, cand_capacity=512,
        flat_budget_bytes=1 << 12, track_paths=False,
    )
    assert c.unique_state_count() == 288  # the flip changes memory,
    # not exploration
    validate_events(tracer.events)
    modes = [e for e in tracer.events if e["ev"] == "engine_mode"]
    assert modes, "the CHUNKED flip must be observable as an event"
    m = modes[0]
    assert m["mode"] == "chunked"
    assert m["engine"] == "SortMergeTpuBfsChecker"
    assert m["chunks"] >= 1 and m["chunk_rows"] >= 1
    assert m["flat_budget_bytes"] == 1 << 12
    # the plan's class ledger agrees with the event
    plan = next(e for e in tracer.events if e["ev"] == "memory_plan")
    assert any(cl["mode"] == "chunked" for cl in plan["classes"])
    # ... and the default-budget run does NOT flip
    tracer2, _ = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256, cand_capacity=512,
        track_paths=False,
    )
    assert not [e for e in tracer2.events
                if e["ev"] == "engine_mode"]


# -- occupancy warnings with byte figures --------------------------------


def test_occupancy_warning_includes_bytes():
    from stateright_tpu.occupancy import occupancy_warning

    msg = occupancy_warning(
        0.9, used=900, capacity=1000, bytes_per_row=8,
    )
    assert msg is not None
    assert "(900/1000)" in msg
    # rendered by the ONE repo-wide byte formatter (memplan)
    assert "[7.03 KB of 7.81 KB]" in msg
    assert memplan.format_bytes(900 * 8) == "7.03 KB"
    # without the ledger's per-row cost the line stays as before
    msg2 = occupancy_warning(0.9, used=900, capacity=1000)
    assert "[" not in msg2
    # under threshold: silent either way
    assert occupancy_warning(0.5, bytes_per_row=8) is None


def test_hash_engine_probe_warning_prices_bytes():
    c = _twopc_builder().spawn_tpu(
        capacity=1 << 9, frontier_capacity=256, track_paths=False,
    ).join()
    assert c.unique_state_count() == 288
    with pytest.warns(RuntimeWarning, match=r"\[.*KB of .*KB\]"):
        c._maybe_warn_occupancy(0.9)


def test_shard_balance_warnings_price_bytes():
    # Synthetic mesh trace: one shard's visited array near capacity;
    # the lane carries the ledger's per-row costs.
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane=dict(
            engine="ShardedSortMergeTpuBfsChecker", capacity=100,
            visited_exact=True, dest_tile_lanes=10,
            visited_row_bytes=8,
        ))
        tr.record_chunk(
            chunk=0, wave0=0, t0=0.0, t1=1.0,
            dispatch_sec=0.5, fetch_sec=0.5,
            wave_rows=[[20, 10, 10, 10, 110, 1, 0, 0]],
            pairs_valid=False,
            shard_rows=[
                [[10, 5, 5, 2, 3, 9, 10, 5, 95]],
                [[10, 5, 5, 2, 3, 9, 10, 5, 15]],
            ],
        )
        tr.end_run()
    bal = telemetry.shard_balance(tr.events)
    assert bal is not None
    vis_warns = [w for w in bal["warnings"] if "visited array" in w]
    assert vis_warns, bal["warnings"]
    # 95 rows x 8 B of 100 x 8 B
    assert "[760 B of 800 B]" in vis_warns[0]
    tile_warns = [w for w in bal["warnings"] if "dest tile" in w]
    assert tile_warns and "[360 B of 400 B]" in tile_warns[0]


# -- mem_report CLI -------------------------------------------------------


def _write_toy_trace(tmp_path, name="mem.jsonl"):
    tracer, c = _traced_checker(
        lambda **kw: _twopc_builder().spawn_tpu_sortmerge(**kw),
        capacity=1 << 10, frontier_capacity=256,
        cand_capacity=1024, track_paths=False,
    )
    path = str(tmp_path / name)
    tracer.write_jsonl(path)
    return path


def _run_tool(tool, *args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", tool),
         *args],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_mem_report_renders_and_writes_artifact(tmp_path):
    trace = _write_toy_trace(tmp_path)
    r = _run_tool("mem_report.py", trace)
    assert r.returncode == 0, r.stderr
    assert "resident-buffer ledger" in r.stdout
    assert "vkeys" in r.stdout
    assert "run peak:" in r.stdout
    assert "projection (next v-class)" in r.stdout
    # --json: MEM numbers in its OWN sequence through artifacts.py
    out = str(tmp_path / "artifacts")
    os.makedirs(out)
    r1 = _run_tool("mem_report.py", trace, "--json", "--root", out)
    assert r1.returncode == 0, r1.stderr
    assert os.path.exists(os.path.join(out, "MEM_r01.json"))
    r2 = _run_tool("mem_report.py", trace, "--json", "--root", out)
    assert r2.returncode == 0
    assert os.path.exists(os.path.join(out, "MEM_r02.json"))
    with open(os.path.join(out, "MEM_r01.json")) as fh:
        doc = json.load(fh)
    assert doc["trace"] == os.path.basename(trace)
    assert doc["plan"]["resident_bytes"] > 0
    assert doc["provenance"]["backend"] == "cpu"


def test_mem_report_exit_2_without_memory_events(tmp_path):
    # a committed pre-round-12 trace has waves but no memory events
    r = _run_tool(
        "mem_report.py", os.path.join(REPO_ROOT, "TRACE_r07.jsonl")
    )
    assert r.returncode == 2
    assert "no memory events" in r.stderr
    assert memory_summary(
        load_trace(os.path.join(REPO_ROOT, "TRACE_r07.jsonl"))
    ) is None
    # bad input: exit 2 as well
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    r2 = _run_tool("mem_report.py", str(bad))
    assert r2.returncode == 2
    # unknown run index
    trace = _write_toy_trace(tmp_path)
    r3 = _run_tool("mem_report.py", trace, "--run", "7")
    assert r3.returncode == 2


# -- trace_diff memory alignment -----------------------------------------


def _synthetic_mem_events(peak=10 << 20, shape=(2, 1280),
                          temp=5 << 20):
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane=dict(engine="X"))
        tr.event(
            "memory_plan", engine="X",
            resident=[dict(name="vkeys", shape=list(shape),
                           dtype="uint32",
                           bytes=int(np.prod(shape)) * 4)],
            resident_bytes=int(np.prod(shape)) * 4,
            classes=[dict(f_class=0, mode="sparse",
                          staging_bytes=64)],
            compiled=dict(temp_size_in_bytes=temp),
            total_bytes=int(np.prod(shape)) * 4 + 64,
        )
        tr.event(
            "memory_watermark", source="live_arrays",
            device_peak_bytes=peak, polls=3,
            headroom={}, projection={},
        )
        tr.end_run()
    return tr.events


def test_trace_diff_plan_shapes_exact():
    a = _synthetic_mem_events()
    # identical → clean
    rep = diff_traces(a, _synthetic_mem_events())
    assert rep["ok"] and not rep["memory"]["divergences"]
    # a changed resident shape is a DIVERGENCE, not a threshold miss
    b = _synthetic_mem_events(shape=(2, 2560))
    rep2 = diff_traces(a, b, threshold=100.0)
    assert not rep2["ok"]
    assert any(d["field"] == "memory_plan"
               for d in rep2["memory"]["divergences"])
    assert "memory-plan divergence" in format_diff(rep2).lower()
    # a changed CLASS names the class and the field that moved
    # (bare equal-length counts would be unactionable)
    c = _synthetic_mem_events()
    plan_c = next(e for e in c if e["ev"] == "memory_plan")
    plan_c["classes"][0]["staging_bytes"] = 999
    rep3 = diff_traces(a, c)
    assert not rep3["ok"]
    cls = [d for d in rep3["memory"]["divergences"]
           if d["field"] == "memory_plan_classes"]
    assert cls and cls[0]["name"] == "class 0.staging_bytes"
    assert cls[0]["a"] == 64 and cls[0]["b"] == 999


def test_trace_diff_skips_memory_against_pre_round12_baseline():
    """A side with no memory events (a committed pre-round-12 trace)
    is not comparable on the memory axis — the diff must SKIP it,
    not fail the gate (chip A/Bs run against old baselines)."""
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane={})
        tr.end_run()
    old = tr.events  # no memory events at all
    new = _synthetic_mem_events()
    for a, b in ((old, new), (new, old)):
        rep = diff_traces(a, b)
        assert not rep["memory"]["divergences"]
        assert not rep["memory"]["regressions"]
        assert rep["ok"]


def test_trace_diff_measured_bytes_under_threshold():
    a = _synthetic_mem_events(peak=10 << 20, temp=10 << 20)
    # +5% live peak and temp: inside the default 10% bar
    b = _synthetic_mem_events(peak=int(10.5 * (1 << 20)),
                              temp=int(10.5 * (1 << 20)))
    rep = diff_traces(a, b)
    assert rep["ok"], rep["memory"]
    assert rep["memory"]["bytes"]["device_peak_bytes"]["rel"] == 0.05
    # +50%: past the bar on both measured lanes
    c = _synthetic_mem_events(peak=15 << 20, temp=15 << 20)
    rep2 = diff_traces(a, c)
    assert not rep2["ok"]
    assert set(rep2["memory"]["regressions"]) == {
        "device_peak_bytes", "compiled_temp_bytes"
    }
    assert "REGRESSION" in format_diff(rep2)
    # tiny absolute sizes never regress (the byte noise floor)
    small_a = _synthetic_mem_events(peak=1000, temp=1000)
    small_b = _synthetic_mem_events(peak=2000, temp=2000)
    assert diff_traces(small_a, small_b)["ok"]


def test_trace_diff_cli_memory_divergence_exit_1(tmp_path):
    a_path = tmp_path / "a.jsonl"
    b_path = tmp_path / "b.jsonl"
    with open(a_path, "w") as fh:
        for ev in _synthetic_mem_events():
            fh.write(json.dumps(ev) + "\n")
    with open(b_path, "w") as fh:
        for ev in _synthetic_mem_events(shape=(2, 2560)):
            fh.write(json.dumps(ev) + "\n")
    r = _run_tool("trace_diff.py", str(a_path), str(b_path))
    assert r.returncode == 1
    assert "MEMORY-PLAN DIVERGENCE" in r.stdout
    r2 = _run_tool("trace_diff.py", str(a_path), str(a_path))
    assert r2.returncode == 0


def test_real_traced_ab_diffs_clean(tmp_path):
    """Two traced runs of one workload (cold + warm in one tracer —
    the bench shape) diff to zero divergence INCLUDING the memory
    counters; the timing threshold is loose (walls differ run to
    run), the memory comparison is what this pins."""
    tracer = RunTracer()
    with tracer.activate():
        for _ in range(2):
            c = _twopc_builder().spawn_tpu_sortmerge(
                capacity=1 << 10, frontier_capacity=256,
                cand_capacity=1024, track_paths=False,
            )
            c.join()
            assert c.unique_state_count() == 288
    path = str(tmp_path / "ab.jsonl")
    tracer.write_jsonl(path)
    evs = load_trace(path)
    validate_events(evs)
    rep = diff_traces(evs, evs, run_a=0, run_b=1, threshold=1e9)
    assert not rep["divergences"]
    assert not rep["memory"]["divergences"]
    assert not rep["memory"]["regressions"]
    assert rep["ok"]
