"""Degrade-and-continue gate (``fault`` marker).

The robustness policy layer (stateright_tpu/checkpoint.py
FailurePolicy + the hung-dispatch watchdog in checkers/tpu.py + the
shard-health straggler detector in telemetry.py): the classification
table, watchdog deadline derivation (rolling-max clamp + the
cold-compile first-chunk grace), and straggler-factor edge cases are
pinned as pure-host policy math; the engine cells pin the behaviors —
a PERSISTENT per-shard fault automatically degrades an S=2 mesh to
S=1 and completes to the exact host-oracle count with degrade-aware
trace_diff zero divergence, an injected dispatch hang is detected by
the watchdog within its derived deadline and either recovers from the
snapshot or refuses loudly with the attribution, a collective-seam
raise recovers like any chunk fault, the tiered frontier-headroom
bound pre-checks BEFORE device work (warn/bump/refuse), and a ^C
during the supervised backoff closes the trace run bracket instead of
dying mid-sleep.
"""

import os
import warnings

import numpy as np
import pytest

from stateright_tpu import faultinject
from stateright_tpu.checkpoint import (
    FailurePolicy,
    WatchdogTimeout,
    classify_failure,
    watchdog_deadline,
)
from stateright_tpu.faultinject import InjectedFault, InjectedShardFault
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry import (
    SHARD_LOG_FIELDS,
    RunTracer,
    detect_stragglers,
    diff_traces,
    validate_events,
)

pytestmark = pytest.mark.fault

HOST_2PC4 = 1568  # host-oracle count, pinned in the ckpt gate too


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faultinject.disarm_all()


def _twopc3(**kw):
    return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=1 << 10, frontier_capacity=128, cand_capacity=512,
        waves_per_sync=2, **kw,
    )


def _mesh2pc4(n_shards, **kw):
    # generous PER-SHARD budgets: the degrade cells land the whole
    # space on one surviving shard, which must hold every row
    kw.setdefault("cand_capacity", 4096)
    kw.setdefault("bucket_capacity", 2048)
    return TwoPhaseSys(rm_count=4).checker().spawn_tpu_sharded_sortmerge(
        n_shards=n_shards, capacity=1 << 12,
        frontier_capacity=1024, waves_per_sync=2, **kw,
    )


# -- policy math: the classification table (pure host) --------------------


def test_classification_table():
    assert classify_failure(WatchdogTimeout(3, 5.0)) == ("hang", None)
    assert classify_failure(MemoryError()) == ("oom", None)
    assert classify_failure(
        RuntimeError("RESOURCE_EXHAUSTED: hbm")
    ) == ("oom", None)
    assert classify_failure(
        InjectedShardFault("mid_chunk", 2, 5)
    ) == ("shard_fault", 5)
    assert classify_failure(
        InjectedFault("mid_chunk", 2)
    ) == ("transient", None)
    # exactly ONE sustained straggler attributes a transient fault;
    # an ambiguous signal attributes nothing
    assert classify_failure(
        InjectedFault("mid_chunk", 2), straggler_shards=(3,)
    ) == ("transient", 3)
    assert classify_failure(
        InjectedFault("mid_chunk", 2), straggler_shards=(1, 3)
    ) == ("transient", None)
    assert classify_failure(ValueError("no")) == ("unsupervised", None)


def test_policy_escalation_and_reset():
    p = FailurePolicy(persist_threshold=2)
    assert p.classify(
        InjectedShardFault("mid_chunk", 1, 1)
    ) == ("shard_fault", 1)
    assert p.should_degrade() is None  # one strike is not persistent
    p.classify(InjectedShardFault("mid_chunk", 2, 1))
    assert p.should_degrade() == 1  # same shard twice: persistent
    p.degraded(1)
    assert p.should_degrade() is None  # strikes left with the shard
    # unattributed failures never escalate
    p.classify(InjectedFault("mid_chunk", 3))
    p.classify(InjectedFault("mid_chunk", 4))
    assert p.should_degrade() is None
    with pytest.raises(ValueError):
        FailurePolicy(persist_threshold=0)


# -- policy math: watchdog deadline derivation ----------------------------


def test_watchdog_deadline_policy():
    # no measured chunk wall yet -> the first-chunk grace: the
    # TRACE_r21 17.9 s persistent-cache disk fetch must never be
    # misclassified as a hang
    assert watchdog_deadline(None, 8.0) == 300.0
    assert watchdog_deadline(None, 8.0) > 17.9
    assert watchdog_deadline(None, 8.0, first_grace_sec=42.0) == 42.0
    # a MEASURED near-zero wall (fully compile-attributed) gets the
    # floor, not the grace — the grace is for unmeasured chunk 0 only
    assert watchdog_deadline(0.0, 8.0) == 2.0
    # k x rolling max, clamped to [floor, cap]
    assert watchdog_deadline(1.0, 8.0) == 8.0
    assert watchdog_deadline(0.01, 8.0) == 2.0
    assert watchdog_deadline(1000.0, 8.0) == 600.0
    assert watchdog_deadline(
        0.01, 8.0, floor_sec=0.25, cap_sec=10.0
    ) == 0.25
    with pytest.raises(ValueError):
        watchdog_deadline(1.0, 0)


# -- policy math: straggler-factor edge cases -----------------------------


def _wave_rows(cands):
    ci = SHARD_LOG_FIELDS.index("candidates")
    r = np.zeros((len(cands), len(SHARD_LOG_FIELDS)), np.int64)
    r[:, ci] = cands
    return r


def test_detect_stragglers_edges():
    with pytest.raises(ValueError):
        detect_stragglers(_wave_rows([10, 10]), 1.0)
    # single shard: no median signal
    assert detect_stragglers(_wave_rows([900]), 4.0) == []
    # balanced mesh: clean
    assert detect_stragglers(_wave_rows([100] * 4), 4.0) == []
    # one heavy shard flags, with the ratio attached
    out = detect_stragglers(_wave_rows([100, 100, 100, 900]), 4.0)
    assert [r["shard"] for r in out] == [3]
    assert out[0]["ratio"] == pytest.approx(9.0)
    # just under the factor: clean
    assert detect_stragglers(
        _wave_rows([100, 100, 100, 399]), 4.0
    ) == []
    # the min-median floor: a near-empty seed wave flags nobody
    assert detect_stragglers(_wave_rows([0, 0, 0, 1]), 4.0) == []


def test_shard_health_events_and_sustained_evidence():
    """_note_shard_health emits schema-valid shard_health events and
    builds the sustained-straggler evidence the classifier reads."""
    tr = RunTracer()
    c = _mesh2pc4(4)  # spawn only: mesh + _shard_ids, no device work
    c.straggler_factor = 4.0
    c.straggler_sustain = 2
    ci = SHARD_LOG_FIELDS.index("candidates")
    srows = np.zeros((4, 3, len(SHARD_LOG_FIELDS)), np.int64)
    srows[:, :, ci] = 100
    srows[3, :, ci] = 900  # shard 3 drags every wave
    with tr.activate():
        tr.begin_run(lane={})
        c._note_shard_health(srows, wave0=5)
        tr.end_run()
    validate_events(tr.events)
    evs = [e for e in tr.events if e["ev"] == "shard_health"]
    assert len(evs) == 3
    assert all(e["shard"] == 3 and e["kind"] == "straggler"
               for e in evs)
    assert evs[0]["wave"] == 5 and evs[-1]["wave"] == 7
    assert evs[-1]["sustained"] == 3
    assert c._sustained_stragglers() == (3,)


# -- fault-spec parsing for the new kinds ---------------------------------


def test_parse_spec_new_kinds():
    f = faultinject.parse_spec("hang@mid_chunk:1:20")
    assert f["action"] == "hang" and f["hang_sec"] == 20.0
    f = faultinject.parse_spec("hang@mid_chunk:1")
    assert f["hang_sec"] == faultinject.DEFAULT_HANG_SEC
    f = faultinject.parse_spec("shard_fault@mid_chunk:2:3")
    assert f["shard"] == 3 and f["once"] is False
    with pytest.raises(ValueError):
        faultinject.parse_spec("raise@mid_chunk:1:9")  # stray arg
    with pytest.raises(ValueError):
        faultinject.parse_spec("hang@bogus_site:1")


# -- engine: watchdog detects the hang, recovers or refuses loudly --------


def test_watchdog_hang_recovers_from_snapshot(tmp_path):
    """An injected dispatch hang (no exception — only the watchdog
    can see it) is detected within the derived deadline and the run
    self-recovers from the last snapshot to the exact count."""
    c = _twopc3(checkpoint_every=1,
                checkpoint_path=str(tmp_path / "wd.ckpt"))
    c.retry_backoff_sec = 0.01
    c.watchdog_factor = 2.0
    c.watchdog_floor_sec = 0.3
    c.watchdog_grace_sec = 15.0
    faultinject.arm("hang", "mid_chunk", 1, hang_sec=6.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c.join()
    assert c.unique_state_count() == 288
    assert any("hang" in str(x.message)
               and "supervised recovery" in str(x.message)
               for x in w)


def test_watchdog_refuses_loudly_without_snapshot(tmp_path):
    """With nothing to recover from, the breach raises the
    WatchdogTimeout with its latency attribution — refuse loudly,
    never a hang — and the traced run carries the schema-valid
    watchdog_timeout event."""
    tr = RunTracer()
    c = _twopc3()  # no checkpointing: the supervisor can't retry
    c.watchdog_factor = 2.0
    c.watchdog_floor_sec = 0.3
    c.watchdog_grace_sec = 15.0
    faultinject.arm("hang", "mid_chunk", 1, hang_sec=6.0)
    with pytest.raises(WatchdogTimeout) as ei:
        with tr.activate():
            c.join()
    assert ei.value.chunk == 1
    assert ei.value.deadline_sec <= 15.0
    assert ei.value.attribution["latency"]["chunks"] >= 1
    validate_events(tr.events)
    evs = [e for e in tr.events if e["ev"] == "watchdog_timeout"]
    assert evs and evs[0]["chunk"] == 1
    assert evs[0]["deadline_sec"] > 0


# -- engine: persistent shard fault -> automatic elastic degrade ----------


def test_persistent_shard_fault_degrades_and_continues(tmp_path):
    """The tentpole behavior at tier-1 scale: a persistent per-shard
    device fault on the S=2 virtual mesh strikes the same shard
    across retries, the policy classifies it persistent, and the
    supervisor drops the shard and re-shards the last snapshot onto
    the survivor — the degraded run completes to the exact
    host-oracle count, the fault_degrade event lands, and the
    degrade-aware trace_diff reports ZERO global-counter divergence
    vs the uninterrupted baseline."""
    tr_base = RunTracer()
    with tr_base.activate():
        base = _mesh2pc4(2).join()
    assert base.unique_state_count() == HOST_2PC4
    validate_events(tr_base.events)

    c = _mesh2pc4(2, checkpoint_every=1,
                  checkpoint_path=str(tmp_path / "deg.ckpt"))
    c.degrade_on_fault = True
    c.retry_backoff_sec = 0.01
    faultinject.arm("shard_fault", "mid_chunk", 1, shard=1)
    tr = RunTracer()
    with tr.activate():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            c.join()
    assert c.unique_state_count() == HOST_2PC4
    assert c.n_shards == 1 and c._shard_ids == (0,)
    assert any("DEGRADING" in str(x.message) for x in w)
    validate_events(tr.events)
    deg = [e for e in tr.events if e["ev"] == "fault_degrade"]
    assert deg and deg[0]["from_shards"] == 2 \
        and deg[0]["to_shards"] == 1
    assert deg[0]["excluded_shard"] == 1
    assert deg[0]["reason"] == "shard_fault"
    # counterexample paths survive the degrade (parent log re-routed)
    for name, path in c.discoveries().items():
        prop = c.model.property_by_name(name)
        assert prop.condition(c.model, path.last_state())
    # degrade-aware alignment: global counters EXACT, shard lanes
    # compare within each shard-count segment, verdict OK
    rep = diff_traces(tr_base.events, tr.events)
    assert rep["degrades_b"] and not rep["degrades_a"]
    assert not rep["divergences"], rep["divergences"]
    assert rep["ok"]


def test_degrade_needs_opt_in(tmp_path):
    """Without --degrade-on-fault the persistent fault exhausts the
    retry budget and raises through — the PR 11 contract unchanged."""
    c = _mesh2pc4(2, checkpoint_every=1,
                  checkpoint_path=str(tmp_path / "nodeg.ckpt"))
    c.retry_backoff_sec = 0.01
    c.max_fault_retries = 2
    faultinject.arm("shard_fault", "mid_chunk", 1, shard=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(InjectedShardFault):
            c.join()
    assert c.n_shards == 2  # nothing degraded


# -- engine: collective-seam raise recovers like any chunk fault ----------


def test_collective_seam_raise_recovers(tmp_path):
    c = _mesh2pc4(2, checkpoint_every=1,
                  checkpoint_path=str(tmp_path / "coll.ckpt"))
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "collective_seam", 1, once=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c.join()
    assert c.unique_state_count() == HOST_2PC4
    # the site is mesh-only: a single-chip run never reaches it
    faultinject.arm("raise", "collective_seam", 0, once=True)
    s = _twopc3()
    s.join()
    assert s.unique_state_count() == 288
    assert faultinject.armed()  # still armed: the site never fired


# -- tiered frontier-headroom pre-check (BEFORE device work) --------------


def test_tier_headroom_precheck_warn_bump_refuse():
    def spawn(**kw):
        kw.setdefault("frontier_capacity", 128)
        kw.setdefault("cand_capacity", 512)
        return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
            capacity=1 << 10, waves_per_sync=2, tier_hot_rows=64,
            **kw,
        )

    # default ("warn"): the PR 12 known bound surfaces UP FRONT as a
    # warning naming the knobs, and the run still completes exactly
    c = spawn()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c.join()
    assert c.unique_state_count() == 288
    assert any("frontier-headroom" in str(x.message) for x in w)

    # "refuse": the pinned message, raised BEFORE any device work
    c2 = spawn()
    c2.tier_headroom_policy = "refuse"
    with pytest.raises(ValueError, match="frontier-headroom"):
        c2.join()

    # "bump": frontier_capacity raised to the provable bound (the
    # cand budget) before programs build; counts unchanged
    c3 = spawn()
    c3.tier_headroom_policy = "bump"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c3.join()
    assert c3.frontier_capacity == 512
    assert c3.unique_state_count() == 288
    assert any("bump" in str(x.message) for x in w)

    # a config where the bound provably holds warns nothing
    c4 = spawn(frontier_capacity=512, cand_capacity=512)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c4.join()
    assert c4.unique_state_count() == 288
    assert not any("frontier-headroom" in str(x.message) for x in w)


def test_degrade_aware_diff_keeps_teeth():
    """The degrade-aware alignment skips shard lanes across a
    re-shard but the GLOBAL counters stay fully enforced: a doctored
    unique_total on the degraded side still fails the gate."""

    def mkrun(degrade, doctor=False):
        evs = [dict(ev="run_begin", run=0, t=0.0, schema=1,
                    level="default", provenance={}, lane={})]
        for w in range(4):
            evs.append(dict(
                ev="wave", run=0, wave=w, chunk=w, t0=0.0, t1=0.1,
                t_est=True, frontier_rows=10, enabled_pairs=None,
                candidates=20, new_states=10,
                unique_total=10 * (w + 1), depth=w + 1,
                f_class=0, v_class=0,
            ))
        if degrade:
            evs.insert(3, dict(ev="fault_degrade", run=0,
                               from_shards=2, to_shards=1,
                               reason="shard_fault", wave=1, t=0.0))
            evs.insert(4, dict(ev="restore", run=0, wave=1, depth=1,
                               from_shards=2, to_shards=1, t=0.0))
        if doctor:
            evs[-1]["unique_total"] += 5
        evs.append(dict(ev="run_end", run=0, t=1.0))
        return evs

    rep = diff_traces(mkrun(False), mkrun(True))
    assert rep["ok"] and not rep["divergences"]
    assert rep["degrades_b"] and not rep["degrades_a"]
    rep2 = diff_traces(mkrun(False), mkrun(True, doctor=True))
    assert not rep2["ok"]
    assert any(d["field"] == "unique_total"
               for d in rep2["divergences"])


def test_degrade_aware_shard_segments():
    """Shard lanes skip ONLY where each side's per-wave shard count
    is exactly what its own degrade history predicts: a shard-row
    loss the history does NOT explain (e.g. at a pre-degrade wave)
    still diverges."""

    def mkrun(degrade, shards_at=None):
        evs = [dict(ev="run_begin", run=0, t=0.0, schema=1,
                    level="default", provenance={},
                    lane=dict(n_shards=2))]
        for w in range(4):
            n_sh = (shards_at or {}).get(
                w, 2 if not degrade or w < 2 else 1
            )
            for s in range(n_sh):
                row = dict(ev="shard_wave", run=0, wave=w, chunk=w,
                           shard=s)
                for f in SHARD_LOG_FIELDS:
                    row[f] = 10
                evs.append(row)
            evs.append(dict(
                ev="wave", run=0, wave=w, chunk=w, t0=0.0, t1=0.1,
                t_est=True, frontier_rows=10, enabled_pairs=None,
                candidates=20, new_states=10,
                unique_total=10 * (w + 1), depth=w + 1,
                f_class=0, v_class=0,
            ))
        if degrade:
            evs.insert(1, dict(ev="fault_degrade", run=0,
                               from_shards=2, to_shards=1,
                               reason="shard_fault", wave=2, t=0.0))
        evs.append(dict(ev="run_end", run=0, t=1.0))
        return evs

    base = mkrun(False)
    # S=2 before the degrade wave, S=1 after: fully explained
    rep = diff_traces(base, mkrun(True))
    assert rep["ok"] and not rep["divergences"]
    # a shard row lost at a PRE-degrade wave is NOT explained
    rep2 = diff_traces(base, mkrun(True, shards_at={1: 1}))
    assert not rep2["ok"]
    assert {d["field"] for d in rep2["divergences"]} >= {
        "shard_count"
    }


# -- interruptible supervised backoff -------------------------------------


def test_backoff_interrupt_closes_trace_bracket(tmp_path,
                                                monkeypatch):
    """A ^C during the supervised backoff must close the trace run
    bracket with the error string instead of dying mid-sleep with a
    dangling run_begin (the drive-by hardening pin)."""
    import time as _time
    import types

    from stateright_tpu import checkpoint as ckpt

    def interrupted_sleep(sec):
        raise KeyboardInterrupt()

    # patch the checkpoint module's time reference only: a global
    # time.sleep patch would intercept unrelated subprocess polls
    monkeypatch.setattr(
        ckpt, "time",
        types.SimpleNamespace(sleep=interrupted_sleep,
                              monotonic=_time.monotonic,
                              time=_time.time),
    )
    c = _twopc3(checkpoint_every=1,
                checkpoint_path=str(tmp_path / "ki.ckpt"))
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "mid_chunk", 1)
    tr = RunTracer()
    with pytest.raises(KeyboardInterrupt):
        with tr.activate():
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                c.join()
    ends = [e for e in tr.events if e["ev"] == "run_end"]
    assert ends, "the run bracket was closed"
    assert "KeyboardInterrupt" in (ends[-1].get("error") or "")
    validate_events(tr.events)


# -- CLI flag plumbing ----------------------------------------------------


def test_cli_runtime_flags():
    from stateright_tpu import cli

    try:
        rest = cli._pop_runtime_flags(
            ["2pc", "check-tpu", "3", "--degrade-on-fault",
             "--watchdog=6", "--straggler-factor=4"]
        )
        assert rest == ["2pc", "check-tpu", "3"]
        assert cli._RUNTIME["degrade_on_fault"] is True
        assert cli._RUNTIME["watchdog"] == 6.0
        assert cli._RUNTIME["straggler_factor"] == 4.0
        cli._pop_runtime_flags(["--watchdog"])
        assert cli._RUNTIME["watchdog"] == 8.0  # the default factor
        with pytest.raises(SystemExit):
            cli._pop_runtime_flags(["--watchdog=0"])
        with pytest.raises(SystemExit):
            cli._pop_runtime_flags(["--straggler-factor=1"])
        # the flags land on a spawned device engine
        c = _twopc3()
        cli._RUNTIME.update(degrade_on_fault=True, watchdog=6.0,
                            straggler_factor=4.0)
        cli._apply_runtime(c)
        assert c.degrade_on_fault is True
        assert c.watchdog_factor == 6.0
        assert c.straggler_factor == 4.0
    finally:
        cli._RUNTIME.update(
            checkpoint_every=None, checkpoint_path=None,
            resume=False, resume_any_sha=False, waves_per_sync=None,
            tier_hot_rows=None, degrade_on_fault=False,
            watchdog=None, straggler_factor=None,
        )
