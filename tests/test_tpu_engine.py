"""The TPU wave engine, differentially validated against the host BFS.

Runs on the virtual CPU backend (conftest sets JAX_PLATFORMS=cpu with
an 8-device mesh); identical code runs on real TPU. Ground truth:
2pc 3 RMs = 288 unique states (reference examples/2pc.rs:153-154) and
identical discovered-property sets vs the host oracle — the north-star
acceptance criterion (BASELINE.json).
"""

import numpy as np
import pytest

from stateright_tpu.fixtures import DGraph
from stateright_tpu.model import Property
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.models.two_phase_commit_tpu import TwoPhaseSysEncoded
from stateright_tpu.ops.fingerprint import fingerprint_u32v, fingerprint_u32v_int
from stateright_tpu.ops.hashset import DeviceHashSet, contains, insert, sort_unique


# -- ops ----------------------------------------------------------------


def test_fingerprint_host_device_bit_identical():
    import jax.numpy as jnp

    vecs = np.random.default_rng(0).integers(
        0, 2**32, size=(64, 7), dtype=np.uint32
    )
    np_lo, np_hi = fingerprint_u32v(vecs, np)
    j_lo, j_hi = fingerprint_u32v(jnp.asarray(vecs), jnp)
    assert np.array_equal(np_lo, np.asarray(j_lo))
    assert np.array_equal(np_hi, np.asarray(j_hi))


def test_fingerprint_distinguishes_and_nonzero():
    vecs = np.array(
        [[0, 0, 0], [0, 0, 1], [1, 0, 0], [0, 1, 0]], dtype=np.uint32
    )
    fps = fingerprint_u32v_int(vecs)
    assert len(set(fps.tolist())) == 4
    assert all(fp != 0 for fp in fps.tolist())


def test_fingerprint_avalanche():
    # One-bit input changes flip ~half the output bits.
    rng = np.random.default_rng(1)
    base = rng.integers(0, 2**32, size=(100, 8), dtype=np.uint32)
    flipped = base.copy()
    flipped[:, 3] ^= 1
    d = fingerprint_u32v_int(base) ^ fingerprint_u32v_int(flipped)
    popcount = np.array([bin(x).count("1") for x in d.tolist()])
    assert 20 < popcount.mean() < 44


def test_hashset_insert_and_dedup():
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    keys = rng.integers(1, 2**32, size=(500, 2), dtype=np.uint32)
    lo, hi = jnp.asarray(keys[:, 0]), jnp.asarray(keys[:, 1])
    table = DeviceHashSet.empty(2048, jnp)
    (slo, shi, order), first = sort_unique(lo, hi, jnp)
    table, is_new, overflow, slots = insert(table, slo, shi, first, jnp)
    assert not bool(jnp.any(overflow))
    n_unique = len({(int(a), int(b)) for a, b in keys})
    assert int(jnp.sum(is_new)) == n_unique
    # Slots point at the inserted keys.
    ins = np.asarray(is_new)
    s = np.asarray(slots)[ins]
    assert np.array_equal(np.asarray(table.lo)[s], np.asarray(slo)[ins])
    assert np.array_equal(np.asarray(table.hi)[s], np.asarray(shi)[ins])
    # Second insert of the same keys: nothing new, same slots found.
    table, is_new2, _, slots2 = insert(table, slo, shi, first, jnp)
    assert int(jnp.sum(is_new2)) == 0
    assert np.array_equal(np.asarray(slots2)[ins], s)
    assert bool(jnp.all(contains(table, slo, shi, jnp) | ~first))


def test_hashset_numpy_matches_jax():
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    keys = rng.integers(1, 2**32, size=(300, 2), dtype=np.uint32)
    t_np = DeviceHashSet.empty(1024, np)
    t_j = DeviceHashSet.empty(1024, jnp)
    (slo, shi, _), first = sort_unique(keys[:, 0], keys[:, 1], np)
    t_np, new_np, _, _ = insert(t_np, slo, shi, first, np)
    t_j, new_j, _, _ = insert(
        t_j, jnp.asarray(slo), jnp.asarray(shi), jnp.asarray(first), jnp
    )
    assert np.array_equal(np.asarray(t_j.lo), t_np.lo)
    assert np.array_equal(np.asarray(new_j), new_np)


# -- engine vs host oracle ----------------------------------------------


def test_tpu_2pc_matches_host_288_states():
    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    tpu = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(capacity=1 << 12, frontier_capacity=512, cand_capacity=1024)
        .join()
    )
    assert tpu.unique_state_count() == 288
    assert tpu.unique_state_count() == host.unique_state_count()
    # Identical discovered-property sets (the north-star criterion).
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()


def test_tpu_2pc_counterexample_paths_replay():
    tpu = TwoPhaseSys(rm_count=3).checker().spawn_tpu(capacity=1 << 12, frontier_capacity=512, cand_capacity=1024).join()
    for name, path in tpu.discoveries().items():
        # Replay through the host model: raises if encoding diverges.
        assert len(path) >= 1
        prop = tpu.model.property_by_name(name)
        assert prop.condition(tpu.model, path.last_state())


def test_tpu_2pc_5rms_matches_host():
    tpu = (
        TwoPhaseSys(rm_count=5)
        .checker()
        .spawn_tpu(
            capacity=1 << 15,
            frontier_capacity=1 << 11,
            cand_capacity=1 << 14,
            track_paths=False,
        )
        .join()
    )
    assert tpu.unique_state_count() == 8832


def test_tpu_encode_decode_roundtrip():
    enc = TwoPhaseSysEncoded(3)
    model = enc.host_model
    frontier = list(model.init_states())
    seen = 0
    while frontier and seen < 50:
        state = frontier.pop()
        seen += 1
        vec = enc.encode(state)
        assert enc.decode(vec) == state
        frontier.extend(model.next_states(state))


def test_tpu_eventually_property():
    # DGraph 1->2->3 plus dead-end 1->4; "reaches 3" fails via 4.
    class DGraphEncoded:
        width = 1
        max_actions = 2

        def __init__(self, model):
            self.host_model = model

        def init_vecs(self):
            return np.array([[1]], dtype=np.uint32)

        def encode(self, state):
            return np.array([state], dtype=np.uint32)

        def step_vec(self, vec):
            import jax.numpy as jnp

            node = vec[0]
            # successors: 1 -> {2, 4}; 2 -> {3}
            s1 = jnp.where(node == 1, jnp.uint32(2), jnp.uint32(3))
            v1 = (node == 1) | (node == 2)
            s2 = jnp.uint32(4)
            v2 = node == 1
            return (
                jnp.stack([vec.at[0].set(s1), vec.at[0].set(s2)]),
                jnp.stack([v1, v2]),
            )

        def property_conditions_vec(self, vec):
            import jax.numpy as jnp

            return jnp.stack([vec[0] == 3])

        def within_boundary_vec(self, vec):
            return True

    model = (
        DGraph.with_path([1, 2, 3])
        .path([1, 4])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    checker = model.checker().spawn_tpu(
        encoded=DGraphEncoded(model), capacity=64, frontier_capacity=8
    ).join()
    path = checker.assert_any_discovery("reaches 3")
    assert path.states() == [1, 4]


def test_tpu_rejects_model_without_encoding():
    from stateright_tpu.fixtures import BinaryClock

    with pytest.raises(ValueError):
        BinaryClock().checker().spawn_tpu()


def test_eventually_index_constraint_is_loud():
    """EncodedModel contract (encoding.py): eventually properties must
    sit at property indices < 32 — every device engine refuses early
    and loudly rather than silently wrapping the ebits lane."""
    import pytest

    from stateright_tpu.model import Expectation, Model, Property
    from stateright_tpu.models.increment_tpu import IncrementEncoded

    class ManyProps(Model):
        def __init__(self):
            self._inner = IncrementEncoded(2).host_model

        def init_states(self):
            return self._inner.init_states()

        def actions(self, state):
            return self._inner.actions(state)

        def next_state(self, state, action):
            return self._inner.next_state(state, action)

        def properties(self):
            pad = [
                Property(Expectation.ALWAYS, f"p{i}", lambda m, s: True)
                for i in range(32)
            ]
            return pad + [
                Property(
                    Expectation.EVENTUALLY, "late", lambda m, s: True
                )
            ]

    model = ManyProps()
    with pytest.raises(ValueError, match="indices < 32"):
        model.checker().spawn_tpu_sortmerge(
            encoded=IncrementEncoded(2), capacity=64,
            frontier_capacity=32, cand_capacity=64,
        ).join()
