"""Test configuration.

TPU-engine tests run on a virtual 8-device CPU mesh so multi-chip
sharding (shard_map + all_to_all frontier shuffles) is exercised
without TPU hardware. Must be set before jax is imported anywhere.
"""

import os

# Force CPU even when the environment provides a TPU backend (the
# driver's axon tunnel sets JAX_PLATFORMS=axon): tests must be fast,
# deterministic, and able to fake an 8-device mesh. Real-TPU runs go
# through bench.py, which leaves the environment alone.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
