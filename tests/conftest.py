"""Test configuration.

Tests run on a virtual 8-device CPU mesh (fast, deterministic, no TPU
required); the sharded-engine tests in test_parallel.py exercise the
multi-chip path (shard_map + all_to_all frontier shuffles) on that
mesh. Real-TPU runs go through bench.py, which leaves the platform
selection alone.

The axon sitecustomize force-registers the TPU backend and overrides
the JAX_PLATFORMS env var via jax.config, so forcing CPU requires both
(a) the XLA flag before any backend initializes and (b) an explicit
config update, which beats the plugin's.
"""

import os

if os.environ.get("STPU_TPU_TESTS"):
    # Run the suite against the real device (the TPU-gated tests stop
    # skipping; most tests just run slower through the tunnel).
    import jax  # noqa: E402
else:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
