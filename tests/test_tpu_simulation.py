"""Device simulation checker (checkers/tpu_simulation.py): vmapped
random walks discover the same property set the exhaustive engines do
on violation workloads, and never discover anything the host doesn't."""

from stateright_tpu.models.increment import Increment, IncrementLock
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_tpu_simulation_finds_lost_update():
    host = Increment(thread_count=3).checker().spawn_bfs().join()
    sim = (
        Increment(thread_count=3)
        .checker()
        .spawn_tpu_simulation(n_walks=256, max_steps=16, rounds=2)
        .join()
    )
    assert sim.discovered_property_names() == set(host.discoveries())
    # Discovery fingerprints correspond to real encoded states: the
    # violated always property was seen at a specific state.
    assert "fin" in sim.discovery_fingerprints()


def test_tpu_simulation_no_false_discoveries():
    """increment_lock has no violations; simulation must not invent
    any (always/eventually undiscovered), and reports approximate
    counts like the reference (state_count == unique_state_count)."""
    sim = (
        IncrementLock(thread_count=2)
        .checker()
        .spawn_tpu_simulation(n_walks=128, max_steps=24, rounds=2)
        .join()
    )
    assert sim.discovered_property_names() == set()
    assert sim.state_count() == sim.unique_state_count()
    sim.assert_properties()


def test_tpu_simulation_finds_sometimes_example():
    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    sim = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_simulation(n_walks=512, max_steps=24, rounds=4)
        .join()
    )
    assert sim.discovered_property_names() <= set(host.discoveries())
    # With 2k traces over a 288-state space the sometimes examples are
    # found with overwhelming probability.
    assert sim.discovered_property_names() == set(host.discoveries())


def test_tpu_simulation_reproducible():
    a = (
        Increment(thread_count=3)
        .checker()
        .spawn_tpu_simulation(n_walks=128, max_steps=12, seed=7)
        .join()
    )
    b = (
        Increment(thread_count=3)
        .checker()
        .spawn_tpu_simulation(n_walks=128, max_steps=12, seed=7)
        .join()
    )
    assert a.discovery_fingerprints() == b.discovery_fingerprints()


def test_tpu_simulation_discovery_paths_replay():
    """discoveries() returns REAL paths (VERDICT r3 #9): the frozen
    per-walk fingerprint trace replays through the host model, and the
    path's last state witnesses the discovery."""
    from stateright_tpu.model import Expectation

    model = Increment(thread_count=3)
    sim = (
        Increment(thread_count=3)
        .checker()
        .spawn_tpu_simulation(n_walks=256, max_steps=16, rounds=2)
        .join()
    )
    paths = sim.discoveries()
    assert "fin" in paths
    p = paths["fin"]
    assert len(p.actions()) >= 1
    prop = model.property_by_name("fin")
    assert prop.expectation == Expectation.ALWAYS
    assert not prop.condition(model, p.last_state())


def test_tpu_simulation_fast_mode_refuses_paths():
    sim = (
        Increment(thread_count=3)
        .checker()
        .spawn_tpu_simulation(
            n_walks=256, max_steps=16, rounds=2, track_paths=False
        )
        .join()
    )
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="track_paths"):
        sim.discoveries()
