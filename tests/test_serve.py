"""Resident-service gate (``serve`` marker, stateright_tpu/serve.py).

The multi-tenancy contract: ONE warm process serves concurrent check
sessions and Explorer queries with counts bit-identical to
cold-process runs — paxos 2c/3s = 16,668 and 2pc rm=4 = 1,568 pinned
under real thread concurrency, with zero cross-session telemetry
bleed (every session's trace validates independently and names only
its own lane). Plus: the byte-budget program LRU (eviction forces a
rebuild, counts unaffected), the fingerprint-stable warm-start
re-check (equal counts, zero new waves dispatched), the admission
check refusing oversized sessions BEFORE device work, the
``_report``-seam in_process ledger-tier regression for repeated
in-process checks, the FIFO gate, the generalized Explorer server
registry, and the serve_summary/SERVE_r* derivation.
"""

import io
import json
import threading
import urllib.request

import pytest

from stateright_tpu import cli
from stateright_tpu.serve import (
    AdmissionRefused,
    CheckService,
    FifoLock,
    serve_summary,
)
from stateright_tpu.telemetry import validate_events

pytestmark = pytest.mark.serve


def _wave_events(session):
    return [e for e in session.tracer.events if e["ev"] == "wave"]


def _builds(session, program=None):
    out = [e for e in session.tracer.events
           if e["ev"] == "program_build"]
    if program is not None:
        out = [e for e in out if e["program"] == program]
    return out


# -- concurrent sessions: pinned counts, zero bleed -----------------------


def test_concurrent_sessions_pinned_counts_zero_bleed(tmp_path):
    """The acceptance row: one warm service, concurrent sessions over
    paxos 2c/3s and 2pc rm=4, counts bit-identical to the pinned
    cold-process baselines, and each session's trace validates
    independently with only its own lane's events."""
    service = CheckService(spool_dir=str(tmp_path))
    lanes = [
        ["paxos", "check-tpu", "2"],
        ["2pc", "check-tpu", "4"],
    ]
    results: dict = {}

    def run(i, argv):
        results[i] = service.check(argv)

    threads = [
        threading.Thread(target=run, args=(i, argv))
        for i, argv in enumerate(lanes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    paxos, twopc = results[0], results[1]
    assert paxos.state == "done", paxos.error
    assert twopc.state == "done", twopc.error
    assert paxos.unique == 16668
    assert twopc.unique == 1568
    assert "unique=16668" in paxos.output
    assert "unique=1568" in twopc.output

    # zero cross-session bleed: each trace validates on its own and
    # carries exactly one run whose lane names its own encoding
    for s, enc in ((paxos, "PaxosEncoded"),
                   (twopc, "TwoPhaseSysEncoded")):
        validate_events(s.tracer.events)
        begins = [e for e in s.tracer.events
                  if e["ev"] == "run_begin"]
        assert len(begins) == 1
        assert begins[0]["lane"]["encoding"] == enc
        # every event in this stream belongs to this session's run
        assert {e.get("run") for e in s.tracer.events} == {0}
    # the final wave's running unique total is the pinned count —
    # the per-wave stream really is this session's exploration
    assert _wave_events(paxos)[-1]["unique_total"] == 16668
    assert _wave_events(twopc)[-1]["unique_total"] == 1568

    # the merged service trace validates too, with disjoint runs and
    # session brackets
    merged = service.events()
    validate_events(merged)
    kinds = [e["ev"] for e in merged]
    assert kinds.count("session_begin") == 2
    assert kinds.count("session_end") == 2
    runs = {e["run"] for e in merged if e["ev"] == "run_begin"}
    assert len(runs) == 2


# -- explorer on the same warm process ------------------------------------


def test_explorer_query_on_the_warm_service(tmp_path):
    """≥ 2 check sessions plus an Explorer query on ONE process: the
    Explorer mounts on the service's HTTP server (make_server
    registry), browses answer while a check session runs, the status
    view carries the session registry, and the explorer session's
    request spans land in its own trace."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    service = CheckService(spool_dir=str(tmp_path))
    service.mount_explorer(TwoPhaseSys(rm_count=2).checker(), "2pc")
    server = service.http_server("127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        done = []

        def run_check():
            done.append(service.check(["2pc", "check-tpu", "3"]))

        worker = threading.Thread(target=run_check)
        worker.start()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.status"
        ) as r:
            status = json.loads(r.read())
        assert status["model"] == "TwoPhaseSys"
        assert "service" in status
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.states/"
        ) as r:
            views = json.loads(r.read())
        assert views and "fingerprint" in views[0]
        # the remote-check endpoint (the --connect client's route)
        body = json.dumps(
            {"argv": ["2pc", "check-tpu", "3"]}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/.check", data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req) as r:
            resp = json.loads(r.read())
        assert resp["ok"] is True
        assert "unique=288" in resp["output"]
        worker.join()
        assert done[0].state == "done"
        assert done[0].unique == 288
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.serve/sessions"
        ) as r:
            block = json.loads(r.read())
        states = {s["lane"]: s["state"] for s in block["sessions"]}
        assert states["explore 2pc"] == "serving"
    finally:
        server.shutdown()

    merged = service.events()
    validate_events(merged)
    spans = [e for e in merged
             if e["ev"] == "span"
             and e.get("phase") == "explorer_request"]
    assert len(spans) >= 2
    ex_session = next(s for s in service._sessions
                      if s.kind == "explorer")
    ex_spans = [e for e in ex_session.tracer.events
                if e["ev"] == "span"
                and e["phase"] == "explorer_request"]
    assert len(ex_spans) == len(spans)
    # and the check sessions' traces carry NO explorer spans (bleed)
    for s in service._sessions:
        if s.kind == "check":
            assert not [e for e in s.tracer.events
                        if e.get("phase") == "explorer_request"]


# -- warm start: incremental re-check -------------------------------------


def test_warm_start_recheck_equal_counts_fewer_waves(tmp_path):
    """A re-submitted model whose encoding fingerprint matches the
    retained session resumes from the retained visited set: counts
    equal the cold check, zero NEW waves dispatched (the cold run's
    wave stream vs the warm run's empty one), and the warm session's
    programs came from the in_process tier."""
    service = CheckService(spool_dir=str(tmp_path))
    cold = service.check(["2pc", "check-tpu", "3"])
    assert cold.state == "done" and cold.unique == 288
    assert not cold.warm_start
    assert len(_wave_events(cold)) > 0

    warm = service.check(["2pc", "check-tpu", "3"])
    assert warm.state == "done", warm.error
    assert warm.warm_start is True
    # bit-identical counts (total AND unique: the carry holds both)
    assert warm.unique == cold.unique == 288
    assert warm.total == cold.total
    assert warm.output.split("unique=")[1].split()[0] == \
        cold.output.split("unique=")[1].split()[0]
    # fewer waves dispatched: the retained carry is already done —
    # the warm run settles at its first sync with no new waves
    assert len(_wave_events(warm)) == 0
    assert [e for e in warm.tracer.events if e["ev"] == "restore"]
    prof = [e for e in warm.tracer.events
            if e["ev"] == "latency_profile"][-1]
    assert prof["chunks"] == 1
    assert prof["resumed_from_wave"] is not None
    # the program cache served warm too
    assert any(b["tier"] == "in_process"
               for b in _builds(warm, "programs"))

    # an EDITED model (different fingerprint -> different retained
    # key) runs cold: correctness never rides the cache
    other = service.check(["2pc", "check-tpu", "4"])
    assert other.state == "done" and other.unique == 1568
    assert not other.warm_start
    assert len(_wave_events(other)) > 0


def test_warm_start_disabled_explores_again(tmp_path):
    service = CheckService(spool_dir=str(tmp_path), warm_start=False)
    a = service.check(["2pc", "check-tpu", "3"])
    b = service.check(["2pc", "check-tpu", "3"])
    assert a.unique == b.unique == 288
    assert not b.warm_start
    assert len(_wave_events(b)) > 0


# -- program LRU: byte-budget eviction ------------------------------------


def test_lru_eviction_recompiles_and_matches_counts(tmp_path):
    """A forced-tiny program budget evicts the LRU program; the
    re-submitted query rebuilds (no in_process programs fetch) and
    still reproduces the pinned count."""
    from stateright_tpu.checkers import tpu as _tpu

    service = CheckService(
        spool_dir=str(tmp_path), program_budget_bytes=1,
        warm_start=False,
    )
    a = service.check(["2pc", "check-tpu", "3"])
    assert a.unique == 288
    assert a.program_key is not None
    assert service.lru_bytes() > 1  # one entry always survives

    b = service.check(["2pc", "check-tpu", "4"])
    assert b.unique == 1568
    # b's arrival pushed a's program out of the byte budget
    assert b.evictions and b.evictions[0][0] == a.program_key
    assert not any(
        _tpu._key_hash(k) == a.program_key
        for k in _tpu._CHUNK_CACHE
    )

    c = service.check(["2pc", "check-tpu", "3"])
    assert c.unique == 288  # counts survive eviction
    # the evicted program could NOT be served in-process again
    assert not any(b_ev["tier"] == "in_process"
                   for b_ev in _builds(c, "programs"))

    merged = service.events()
    validate_events(merged)
    ev = [e for e in merged if e["ev"] == "program_evict"]
    assert ev and ev[0]["key"] == a.program_key


# -- admission ------------------------------------------------------------


def test_admission_refuses_oversized_before_device_work(tmp_path):
    service = CheckService(
        spool_dir=str(tmp_path), device_budget_bytes=1024,
    )
    s = service.check(["2pc", "check-tpu", "3"])
    assert s.state == "refused"
    assert "admission refused" in s.error
    assert "REFUSED" in s.output
    # refused BEFORE any program build or device work
    assert s.checker is not None
    assert s.checker._programs is None
    # and a session under the budget still runs (no leaked in-flight
    # accounting from the refused one)
    service.device_budget_bytes = 1 << 30
    ok = service.check(["2pc", "check-tpu", "3"])
    assert ok.state == "done" and ok.unique == 288


def test_runtime_flags_refused():
    service = CheckService()
    with pytest.raises(ValueError, match="plain lane argv"):
        service.check(["2pc", "check-tpu", "3", "--trace"])


# -- the _report seam: in_process second check (satellite) ----------------


def test_second_in_process_check_hits_in_process_tier():
    """Two identical in-process CLI invocations share the one
    ``_report`` seam and therefore the process program cache: the
    second's compile ledger pins the ``in_process`` tier for the
    whole programs pair (the regression this PR's seam factoring
    must keep true — a resident service without it would recompile
    per query)."""
    from stateright_tpu.telemetry import RunTracer

    buf = io.StringIO()

    def run():
        tr = RunTracer()
        with tr.activate_thread():
            cli.main(["increment", "check-tpu", "2"])
        return tr

    import contextlib

    with contextlib.redirect_stdout(buf):
        run()  # builds (cold or disk — whatever this process paid)
        tr2 = run()
    progs = [e for e in tr2.events if e["ev"] == "program_build"
             and e["program"] == "programs"]
    assert progs and progs[0]["tier"] == "in_process"
    # in_process means NO XLA work: the ledger's wall is the fetch
    assert progs[0]["cold_sec"] == 0.0


# -- FIFO gate ------------------------------------------------------------


def test_fifo_lock_is_arrival_ordered():
    import time

    lock = FifoLock()
    order = []
    lock.acquire()

    def waiter(i):
        def run():
            with lock:
                order.append(i)

        t = threading.Thread(target=run)
        t.start()
        # wait until this waiter is actually ENQUEUED before the next
        # arrives — arrival order is what the lock must preserve
        deadline = time.monotonic() + 5.0
        while len(lock._waiters) < i + 1:
            assert time.monotonic() < deadline
            time.sleep(0.001)
        return t

    threads = [waiter(i) for i in range(4)]
    lock.release()
    for t in threads:
        t.join()
    assert order == [0, 1, 2, 3]


# -- summary + artifact derivation ----------------------------------------


def test_serve_summary_and_artifact(tmp_path):
    service = CheckService(spool_dir=str(tmp_path))
    service.check(["2pc", "check-tpu", "3"])
    service.check(["2pc", "check-tpu", "3"])
    jsonl, chrome = service.write_trace(root=str(tmp_path))
    assert "TRACE_r01" in jsonl

    from stateright_tpu.telemetry import load_trace

    events = load_trace(jsonl)
    validate_events(events)
    summary = serve_summary(events)
    assert summary is not None
    assert len(summary["sessions"]) == 2
    s0, s1 = summary["sessions"]
    assert s0["unique"] == s1["unique"] == 288
    assert s0["warm_start"] is False and s1["warm_start"] is True
    assert s0["time_to_verdict_sec"] is not None
    assert s1["time_to_verdict_sec"] is not None
    wvc = summary["warm_vs_cold"]
    assert len(wvc) == 1
    assert wvc[0]["cold_session"] == s0["session"]
    assert wvc[0]["warm_session"] == s1["session"]
    assert wvc[0]["ttv_delta_sec"] is not None

    # the report renders and the artifact round-trips
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(cli.__file__)
    ))
    out = subprocess.run(
        [_sys.executable,
         os.path.join(repo, "tools", "serve_report.py"),
         jsonl, "--json", "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "serve report" in out.stdout
    assert "warm vs cold" in out.stdout
    serve_artifacts = list(tmp_path.glob("SERVE_r*.json"))
    assert len(serve_artifacts) == 1
    with open(serve_artifacts[0]) as fh:
        doc = json.load(fh)
    assert doc["trace"] == "TRACE_r01.jsonl"
    assert len(doc["sessions"]) == 2
    assert doc["provenance"]["git_sha"] is not None

    from stateright_tpu.artifacts import latest_serve_summary

    ref = latest_serve_summary(root=str(tmp_path))
    assert ref is not None
    assert ref["artifact"] == serve_artifacts[0].name
    assert ref["sessions"] == 2
    assert ref["warm_vs_cold"] is not None


def test_serve_report_rejects_non_service_trace(tmp_path):
    """serve_report exits 2 on a trace with no session events."""
    from stateright_tpu.serve import serve_summary as ss

    assert ss([{"ev": "run_begin", "run": 0}]) is None


# -- make_server registry stays compatible --------------------------------


def test_make_server_requires_checker_or_registry():
    from stateright_tpu.explorer.server import Snapshot, make_server

    with pytest.raises(ValueError, match="checker, a registry"):
        make_server(None, Snapshot(), "127.0.0.1", 0)
