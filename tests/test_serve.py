"""Resident-service gate (``serve`` marker, stateright_tpu/serve.py).

The multi-tenancy contract: ONE warm process serves concurrent check
sessions and Explorer queries with counts bit-identical to
cold-process runs — paxos 2c/3s = 16,668 and 2pc rm=4 = 1,568 pinned
under real thread concurrency, with zero cross-session telemetry
bleed (every session's trace validates independently and names only
its own lane). Plus: the byte-budget program LRU (eviction forces a
rebuild, counts unaffected), the fingerprint-stable warm-start
re-check (equal counts, zero new waves dispatched), the admission
check refusing oversized sessions BEFORE device work, the
``_report``-seam in_process ledger-tier regression for repeated
in-process checks, the FIFO gate, the generalized Explorer server
registry, and the serve_summary/SERVE_r* derivation.
"""

import io
import json
import threading
import urllib.request

import pytest

from stateright_tpu import cli
from stateright_tpu.serve import (
    AdmissionRefused,
    CheckService,
    FifoLock,
    serve_summary,
)
from stateright_tpu.telemetry import validate_events

pytestmark = pytest.mark.serve


def _wave_events(session):
    return [e for e in session.tracer.events if e["ev"] == "wave"]


def _builds(session, program=None):
    out = [e for e in session.tracer.events
           if e["ev"] == "program_build"]
    if program is not None:
        out = [e for e in out if e["program"] == program]
    return out


# -- concurrent sessions: pinned counts, zero bleed -----------------------


def test_concurrent_sessions_pinned_counts_zero_bleed(tmp_path):
    """The acceptance row: one warm service, concurrent sessions over
    paxos 2c/3s and 2pc rm=4, counts bit-identical to the pinned
    cold-process baselines, and each session's trace validates
    independently with only its own lane's events."""
    service = CheckService(spool_dir=str(tmp_path))
    lanes = [
        ["paxos", "check-tpu", "2"],
        ["2pc", "check-tpu", "4"],
    ]
    results: dict = {}

    def run(i, argv):
        results[i] = service.check(argv)

    threads = [
        threading.Thread(target=run, args=(i, argv))
        for i, argv in enumerate(lanes)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    paxos, twopc = results[0], results[1]
    assert paxos.state == "done", paxos.error
    assert twopc.state == "done", twopc.error
    assert paxos.unique == 16668
    assert twopc.unique == 1568
    assert "unique=16668" in paxos.output
    assert "unique=1568" in twopc.output

    # zero cross-session bleed: each trace validates on its own and
    # carries exactly one run whose lane names its own encoding
    for s, enc in ((paxos, "PaxosEncoded"),
                   (twopc, "TwoPhaseSysEncoded")):
        validate_events(s.tracer.events)
        begins = [e for e in s.tracer.events
                  if e["ev"] == "run_begin"]
        assert len(begins) == 1
        assert begins[0]["lane"]["encoding"] == enc
        # every event in this stream belongs to this session's run
        assert {e.get("run") for e in s.tracer.events} == {0}
    # the final wave's running unique total is the pinned count —
    # the per-wave stream really is this session's exploration
    assert _wave_events(paxos)[-1]["unique_total"] == 16668
    assert _wave_events(twopc)[-1]["unique_total"] == 1568

    # the merged service trace validates too, with disjoint runs and
    # session brackets
    merged = service.events()
    validate_events(merged)
    kinds = [e["ev"] for e in merged]
    assert kinds.count("session_begin") == 2
    assert kinds.count("session_end") == 2
    runs = {e["run"] for e in merged if e["ev"] == "run_begin"}
    assert len(runs) == 2


# -- explorer on the same warm process ------------------------------------


def test_explorer_query_on_the_warm_service(tmp_path):
    """≥ 2 check sessions plus an Explorer query on ONE process: the
    Explorer mounts on the service's HTTP server (make_server
    registry), browses answer while a check session runs, the status
    view carries the session registry, and the explorer session's
    request spans land in its own trace."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    service = CheckService(spool_dir=str(tmp_path))
    service.mount_explorer(TwoPhaseSys(rm_count=2).checker(), "2pc")
    server = service.http_server("127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        done = []

        def run_check():
            done.append(service.check(["2pc", "check-tpu", "3"]))

        worker = threading.Thread(target=run_check)
        worker.start()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.status"
        ) as r:
            status = json.loads(r.read())
        assert status["model"] == "TwoPhaseSys"
        assert "service" in status
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.states/"
        ) as r:
            views = json.loads(r.read())
        assert views and "fingerprint" in views[0]
        # the remote-check endpoint (the --connect client's route)
        body = json.dumps(
            {"argv": ["2pc", "check-tpu", "3"]}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/.check", data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req) as r:
            resp = json.loads(r.read())
        assert resp["ok"] is True
        assert "unique=288" in resp["output"]
        worker.join()
        assert done[0].state == "done"
        assert done[0].unique == 288
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.serve/sessions"
        ) as r:
            block = json.loads(r.read())
        states = {s["lane"]: s["state"] for s in block["sessions"]}
        assert states["explore 2pc"] == "serving"
    finally:
        server.shutdown()

    merged = service.events()
    validate_events(merged)
    spans = [e for e in merged
             if e["ev"] == "span"
             and e.get("phase") == "explorer_request"]
    assert len(spans) >= 2
    ex_session = next(s for s in service._sessions
                      if s.kind == "explorer")
    ex_spans = [e for e in ex_session.tracer.events
                if e["ev"] == "span"
                and e["phase"] == "explorer_request"]
    assert len(ex_spans) == len(spans)
    # and the check sessions' traces carry NO explorer spans (bleed)
    for s in service._sessions:
        if s.kind == "check":
            assert not [e for e in s.tracer.events
                        if e.get("phase") == "explorer_request"]


# -- warm start: incremental re-check -------------------------------------


def test_warm_start_recheck_equal_counts_fewer_waves(tmp_path):
    """A re-submitted model whose encoding fingerprint matches the
    retained session resumes from the retained visited set: counts
    equal the cold check, zero NEW waves dispatched (the cold run's
    wave stream vs the warm run's empty one), and the warm session's
    programs came from the in_process tier."""
    service = CheckService(spool_dir=str(tmp_path))
    cold = service.check(["2pc", "check-tpu", "3"])
    assert cold.state == "done" and cold.unique == 288
    assert not cold.warm_start
    assert len(_wave_events(cold)) > 0

    warm = service.check(["2pc", "check-tpu", "3"])
    assert warm.state == "done", warm.error
    assert warm.warm_start is True
    # bit-identical counts (total AND unique: the carry holds both)
    assert warm.unique == cold.unique == 288
    assert warm.total == cold.total
    assert warm.output.split("unique=")[1].split()[0] == \
        cold.output.split("unique=")[1].split()[0]
    # fewer waves dispatched: the retained carry is already done —
    # the warm run settles at its first sync with no new waves
    assert len(_wave_events(warm)) == 0
    assert [e for e in warm.tracer.events if e["ev"] == "restore"]
    prof = [e for e in warm.tracer.events
            if e["ev"] == "latency_profile"][-1]
    assert prof["chunks"] == 1
    assert prof["resumed_from_wave"] is not None
    # the program cache served warm too
    assert any(b["tier"] == "in_process"
               for b in _builds(warm, "programs"))

    # an EDITED model (different fingerprint -> different retained
    # key) runs cold: correctness never rides the cache
    other = service.check(["2pc", "check-tpu", "4"])
    assert other.state == "done" and other.unique == 1568
    assert not other.warm_start
    assert len(_wave_events(other)) > 0


def test_warm_start_disabled_explores_again(tmp_path):
    service = CheckService(spool_dir=str(tmp_path), warm_start=False)
    a = service.check(["2pc", "check-tpu", "3"])
    b = service.check(["2pc", "check-tpu", "3"])
    assert a.unique == b.unique == 288
    assert not b.warm_start
    assert len(_wave_events(b)) > 0


# -- program LRU: byte-budget eviction ------------------------------------


def test_lru_eviction_recompiles_and_matches_counts(tmp_path):
    """A forced-tiny program budget evicts the LRU program; the
    re-submitted query rebuilds (no in_process programs fetch) and
    still reproduces the pinned count."""
    from stateright_tpu.checkers import tpu as _tpu

    service = CheckService(
        spool_dir=str(tmp_path), program_budget_bytes=1,
        warm_start=False,
    )
    # the round-19 repeat-fingerprint prewarm would pay the rebuild
    # on its worker thread (it has its own test); disable it so the
    # RUN's own lookup pays it and the ledger shows the eviction
    service._prewarm = lambda *a, **kw: None
    a = service.check(["2pc", "check-tpu", "3"])
    assert a.unique == 288
    assert a.program_key is not None
    assert service.lru_bytes() > 1  # one entry always survives

    b = service.check(["2pc", "check-tpu", "4"])
    assert b.unique == 1568
    # b's arrival pushed a's program out of the byte budget
    assert b.evictions and b.evictions[0][0] == a.program_key
    assert not any(
        _tpu._key_hash(k) == a.program_key
        for k in _tpu._CHUNK_CACHE
    )

    c = service.check(["2pc", "check-tpu", "3"])
    assert c.unique == 288  # counts survive eviction
    # the evicted program could NOT be served in-process again
    assert not any(b_ev["tier"] == "in_process"
                   for b_ev in _builds(c, "programs"))

    merged = service.events()
    validate_events(merged)
    ev = [e for e in merged if e["ev"] == "program_evict"]
    assert ev and ev[0]["key"] == a.program_key


# -- admission ------------------------------------------------------------


def test_admission_refuses_oversized_before_device_work(tmp_path):
    service = CheckService(
        spool_dir=str(tmp_path), device_budget_bytes=1024,
    )
    s = service.check(["2pc", "check-tpu", "3"])
    assert s.state == "refused"
    assert "admission refused" in s.error
    assert "REFUSED" in s.output
    # refused BEFORE any program build or device work
    assert s.checker is not None
    assert s.checker._programs is None
    # and a session under the budget still runs (no leaked in-flight
    # accounting from the refused one)
    service.device_budget_bytes = 1 << 30
    ok = service.check(["2pc", "check-tpu", "3"])
    assert ok.state == "done" and ok.unique == 288


def test_runtime_flags_refused():
    service = CheckService()
    with pytest.raises(ValueError, match="plain lane argv"):
        service.check(["2pc", "check-tpu", "3", "--trace"])


# -- the _report seam: in_process second check (satellite) ----------------


def test_second_in_process_check_hits_in_process_tier():
    """Two identical in-process CLI invocations share the one
    ``_report`` seam and therefore the process program cache: the
    second's compile ledger pins the ``in_process`` tier for the
    whole programs pair (the regression this PR's seam factoring
    must keep true — a resident service without it would recompile
    per query)."""
    from stateright_tpu.telemetry import RunTracer

    buf = io.StringIO()

    def run():
        tr = RunTracer()
        with tr.activate_thread():
            cli.main(["increment", "check-tpu", "2"])
        return tr

    import contextlib

    with contextlib.redirect_stdout(buf):
        run()  # builds (cold or disk — whatever this process paid)
        tr2 = run()
    progs = [e for e in tr2.events if e["ev"] == "program_build"
             and e["program"] == "programs"]
    assert progs and progs[0]["tier"] == "in_process"
    # in_process means NO XLA work: the ledger's wall is the fetch
    assert progs[0]["cold_sec"] == 0.0


# -- FIFO gate ------------------------------------------------------------


def test_fifo_lock_is_arrival_ordered():
    import time

    lock = FifoLock()
    order = []
    lock.acquire()

    def waiter(i):
        def run():
            with lock:
                order.append(i)

        t = threading.Thread(target=run)
        t.start()
        # wait until this waiter is actually ENQUEUED before the next
        # arrives — arrival order is what the lock must preserve
        deadline = time.monotonic() + 5.0
        while len(lock._waiters) < i + 1:
            assert time.monotonic() < deadline
            time.sleep(0.001)
        return t

    threads = [waiter(i) for i in range(4)]
    lock.release()
    for t in threads:
        t.join()
    assert order == [0, 1, 2, 3]


# -- summary + artifact derivation ----------------------------------------


def test_serve_summary_and_artifact(tmp_path):
    service = CheckService(spool_dir=str(tmp_path))
    service.check(["2pc", "check-tpu", "3"])
    service.check(["2pc", "check-tpu", "3"])
    jsonl, chrome = service.write_trace(root=str(tmp_path))
    assert "TRACE_r01" in jsonl

    from stateright_tpu.telemetry import load_trace

    events = load_trace(jsonl)
    validate_events(events)
    summary = serve_summary(events)
    assert summary is not None
    assert len(summary["sessions"]) == 2
    s0, s1 = summary["sessions"]
    assert s0["unique"] == s1["unique"] == 288
    assert s0["warm_start"] is False and s1["warm_start"] is True
    assert s0["time_to_verdict_sec"] is not None
    assert s1["time_to_verdict_sec"] is not None
    wvc = summary["warm_vs_cold"]
    assert len(wvc) == 1
    assert wvc[0]["cold_session"] == s0["session"]
    assert wvc[0]["warm_session"] == s1["session"]
    assert wvc[0]["ttv_delta_sec"] is not None

    # the report renders and the artifact round-trips
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(cli.__file__)
    ))
    out = subprocess.run(
        [_sys.executable,
         os.path.join(repo, "tools", "serve_report.py"),
         jsonl, "--json", "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "serve report" in out.stdout
    assert "warm vs cold" in out.stdout
    serve_artifacts = list(tmp_path.glob("SERVE_r*.json"))
    assert len(serve_artifacts) == 1
    with open(serve_artifacts[0]) as fh:
        doc = json.load(fh)
    assert doc["trace"] == "TRACE_r01.jsonl"
    assert len(doc["sessions"]) == 2
    assert doc["provenance"]["git_sha"] is not None

    from stateright_tpu.artifacts import latest_serve_summary

    ref = latest_serve_summary(root=str(tmp_path))
    assert ref is not None
    assert ref["artifact"] == serve_artifacts[0].name
    assert ref["sessions"] == 2
    assert ref["warm_vs_cold"] is not None


def test_serve_report_rejects_non_service_trace(tmp_path):
    """serve_report exits 2 on a trace with no session events."""
    from stateright_tpu.serve import serve_summary as ss

    assert ss([{"ev": "run_begin", "run": 0}]) is None


# -- make_server registry stays compatible --------------------------------


def test_make_server_requires_checker_or_registry():
    from stateright_tpu.explorer.server import Snapshot, make_server

    with pytest.raises(ValueError, match="checker, a registry"):
        make_server(None, Snapshot(), "127.0.0.1", 0)


# -- wave batching: fused multi-session dispatch --------------------------


def _concurrent(service, lanes, stagger_sec=0.0):
    """Submit lanes on real threads (staggered when the join ORDER
    matters — seat 0 leads the fused dispatch) and return the
    sessions in submission order."""
    import time as _time

    results: dict = {}

    def run(i, argv):
        results[i] = service.check(argv)

    threads = []
    for i, argv in enumerate(lanes):
        t = threading.Thread(target=run, args=(i, argv))
        t.start()
        threads.append(t)
        if stagger_sec and i + 1 < len(lanes):
            _time.sleep(stagger_sec)
    for t in threads:
        t.join()
    return [results[i] for i in range(len(lanes))]


def test_batched_sessions_pinned_counts_zero_bleed(tmp_path):
    """The batching acceptance row (ISSUE 16 tests a+b): four
    concurrent sessions — paxos 2c/3s x2 and 2pc rm=4 x2 — fuse into
    TWO groups (paxos and 2pc encode to different compatibility
    classes), every lane reproduces its pinned solo count, and each
    session's trace validates independently with only its own lane's
    events (zero cross-session bleed through the fused dispatch)."""
    service = CheckService(
        spool_dir=str(tmp_path), warm_start=False,
        batch_sessions=2, batch_window_sec=30.0,
    )
    sessions = _concurrent(service, [
        ["paxos", "check-tpu", "2"],
        ["paxos", "check-tpu", "2"],
        ["2pc", "check-tpu", "4"],
        ["2pc", "check-tpu", "4"],
    ])
    pinned = {"paxos": 16668, "2pc": 1568}
    for s in sessions:
        assert s.state == "done", s.error
        assert s.unique == pinned[s.argv[0]]
        assert f"unique={pinned[s.argv[0]]}" in s.output
        # every seat actually rode a size-2 fused dispatch
        assert s.batch is not None and s.batch["size"] == 2

    # different encoding shapes never share a group
    paxos_groups = {s.batch["group"] for s in sessions[:2]}
    twopc_groups = {s.batch["group"] for s in sessions[2:]}
    assert len(paxos_groups) == len(twopc_groups) == 1
    assert paxos_groups != twopc_groups

    # zero cross-session bleed: each member trace validates on its
    # own, names only its own lane, and its per-wave running unique
    # total lands on the pinned count
    for s, enc in zip(sessions, ("PaxosEncoded",) * 2
                      + ("TwoPhaseSysEncoded",) * 2):
        validate_events(s.tracer.events)
        begins = [e for e in s.tracer.events if e["ev"] == "run_begin"]
        assert len(begins) == 1
        assert begins[0]["lane"]["encoding"] == enc
        assert {e.get("run") for e in s.tracer.events} == {0}
        assert _wave_events(s)[-1]["unique_total"] == \
            pinned[s.argv[0]]
        # the batch marker rode the trace too (serve_summary demuxes
        # groups from it)
        marks = [e for e in s.tracer.events if e["ev"] == "batch"]
        assert len(marks) == 1 and marks[0]["size"] == 2

    # the merged service trace validates with disjoint runs, and the
    # summary's batches block shows both groups fully occupied
    merged = service.events()
    validate_events(merged)
    summary = serve_summary(merged)
    batches = summary["batches"]
    assert len(batches) == 2
    for g in batches:
        assert g["size"] == 2 and len(g["sessions"]) == 2
        assert g["per_query_overhead_sec"] is not None


def test_batched_vs_solo_trace_diff_zero_divergence(tmp_path):
    """trace_diff treats a batched member run vs a solo run of the
    same model as comparable with ZERO counter divergence — the
    per-wave proof that the sid-partition keeps each session's
    frontier/candidate/new/unique stream bit-exact through the fused
    dispatch."""
    from stateright_tpu.telemetry import diff_traces

    service = CheckService(
        spool_dir=str(tmp_path), warm_start=False,
        batch_sessions=2, batch_window_sec=30.0,
    )
    batched = _concurrent(service, [
        ["2pc", "check-tpu", "4"],
        ["2pc", "check-tpu", "4"],
    ])
    solo_dir = tmp_path / "solo"
    solo_dir.mkdir()
    solo = CheckService(
        spool_dir=str(solo_dir), warm_start=False,
    ).check(["2pc", "check-tpu", "4"])
    assert solo.unique == 1568
    for s in batched:
        assert s.unique == 1568 and s.batch["size"] == 2
        rep = diff_traces(s.tracer.events, solo.tracer.events)
        assert rep["divergences"] == []
        assert rep["latency"]["divergences"] == []
        assert rep["memory"]["divergences"] == []
        # batched counterexample paths replay like solo ones
        assert sorted(s.checker.discoveries()) == \
            sorted(solo.checker.discoveries())


def test_batch_early_settle_peels_out(tmp_path):
    """ISSUE 16 test c: a session that settles early peels OUT of the
    fused dispatch between chunks — it does not hold the surviving
    session's waves. 2pc rm=3 (11 waves) fuses with rm=4 (14 waves)
    in one class; at waves_per_sync=4 the rm=3 seat wakes after chunk
    3 while rm=4 rides all 4 fused chunks. Seat 0 leads the fused
    run, so the early settler must join second (the stagger)."""
    service = CheckService(
        spool_dir=str(tmp_path), warm_start=False,
        batch_sessions=2, batch_window_sec=30.0,
        batch_waves_per_sync=4,
    )
    big, small = _concurrent(service, [
        ["2pc", "check-tpu", "4"],
        ["2pc", "check-tpu", "3"],
    ], stagger_sec=1.0)
    assert big.state == "done" and big.unique == 1568
    assert small.state == "done" and small.unique == 288
    assert big.batch["index"] == 0 and small.batch["index"] == 1
    assert big.batch["size"] == small.batch["size"] == 2

    def chunks(s):
        prof = [e for e in s.tracer.events
                if e["ev"] == "latency_profile"][-1]
        return prof["chunks"]

    assert chunks(small) < chunks(big)  # peeled out early


def test_batch_incompatible_shapes_fall_back_solo(tmp_path):
    """ISSUE 16 test d: sessions whose encodings land in different
    compatibility classes never fuse — each falls back to the solo
    FIFO gate with a one-line reason in its output, counts
    unaffected."""
    service = CheckService(
        spool_dir=str(tmp_path), warm_start=False,
        batch_sessions=2, batch_window_sec=0.5,
    )
    inc, twopc = _concurrent(service, [
        ["increment", "check-tpu", "2"],
        ["2pc", "check-tpu", "3"],
    ])
    assert inc.state == "done" and twopc.state == "done"
    assert twopc.unique == 288
    for s in (inc, twopc):
        assert s.batch is None  # solo_prepare cleared the seat
        assert ("batch: no compatible peers arrived within the "
                "batching window") in s.output
    # no group ever dispatched
    assert serve_summary(service.events())["batches"] == []


def test_batch_fused_admission_refusal(tmp_path):
    """ISSUE 16 test e: the fused plan is priced via the memplan
    ledger BEFORE device work — when it exceeds the device budget the
    group refuses with a one-line reason and falls back to solo FIFO
    (where each seat faces ordinary solo admission)."""
    service = CheckService(
        spool_dir=str(tmp_path), warm_start=False,
        batch_sessions=2, batch_window_sec=30.0,
        device_budget_bytes=1024,
    )
    sessions = _concurrent(service, [
        ["2pc", "check-tpu", "3"],
        ["2pc", "check-tpu", "3"],
    ])
    for s in sessions:
        assert "batch: fused plan of 2 session(s)" in s.output
        assert "falling back to solo FIFO" in s.output
        # the solo fallback then refused under the same tiny budget,
        # before any program build or device work
        assert s.state == "refused"
        assert "admission refused" in s.error
        assert s.checker._programs is None


# -- admission-time program pre-warm (satellite) --------------------------


def test_prewarm_on_repeat_fingerprint(tmp_path):
    """A repeat encoding fingerprint kicks the program build-or-fetch
    on a worker thread at admission (ROADMAP 3(d)); the joined result
    is ledger-attributed as a ``program_build`` event with a
    ``prewarm`` marker, and counts are unaffected."""
    service = CheckService(spool_dir=str(tmp_path), warm_start=False)
    a = service.check(["2pc", "check-tpu", "3"])
    b = service.check(["2pc", "check-tpu", "3"])
    assert a.unique == b.unique == 288
    assert not [e for e in _builds(a) if e.get("prewarm")]
    pre = [e for e in _builds(b, "programs") if e.get("prewarm")]
    assert len(pre) == 1
    # the tier depends on what the shared XLA caches already hold in
    # this process; the ledger attribution itself is the contract
    assert pre[0]["tier"] in ("in_process", "disk", "cold", "mixed")
    assert pre[0]["wall_sec"] >= 0
    validate_events(b.tracer.events)


# -- snapshot spool: byte-budget LRU (satellite) --------------------------


def test_snapshot_spool_budget_evicts_lru(tmp_path):
    """Retained warm-start snapshots ride the same byte-budget LRU
    policy as compiled programs: a forced-tiny spool budget evicts
    the LRU fingerprint's snapshot (``snapshot_evict`` events), the
    evicted model's next re-check runs cold, and counts never ride
    the cache."""
    service = CheckService(
        spool_dir=str(tmp_path), snapshot_budget_bytes=1,
    )
    a = service.check(["2pc", "check-tpu", "3"])
    b = service.check(["2pc", "check-tpu", "4"])
    assert a.unique == 288 and b.unique == 1568
    # b's retention pushed a's snapshot out of the byte budget (one
    # entry always survives: b's own)
    assert b.snapshot_evictions
    assert service.spool_bytes() > 1

    c = service.check(["2pc", "check-tpu", "3"])
    assert c.unique == 288  # counts survive eviction
    assert not c.warm_start  # the evicted snapshot could not serve
    assert len(_wave_events(c)) > 0

    merged = service.events()
    validate_events(merged)
    ev = [e for e in merged if e["ev"] == "snapshot_evict"]
    assert ev and ev[0]["key"] == b.snapshot_evictions[0][0]
    assert ev[0]["bytes"] == b.snapshot_evictions[0][1]
