"""The sort-merge wave engine (checkers/tpu_sortmerge.py),
differentially validated against the host oracle and the hash-table
engine. Same acceptance bar as test_tpu_engine.py: reference-pinned
counts and identical discovered-property sets.
"""

import pytest

from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_sortmerge_2pc_matches_host_288():
    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    sm = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=512, frontier_capacity=128, cand_capacity=1024
        )
        .join()
    )
    assert sm.unique_state_count() == 288
    assert sorted(sm.discoveries()) == sorted(host.discoveries())
    sm.assert_properties()
    # Counterexample paths replay through the host model (exercises
    # the append-only parent log).
    for name, path in sm.discoveries().items():
        prop = sm.model.property_by_name(name)
        assert prop.condition(sm.model, path.last_state())


def test_sortmerge_agrees_with_hashtable_engine():
    a = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu(
            capacity=1 << 12, frontier_capacity=512, cand_capacity=2048
        )
        .join()
    )
    b = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 12, frontier_capacity=512, cand_capacity=2048
        )
        .join()
    )
    assert a.unique_state_count() == b.unique_state_count()
    assert a.state_count() == b.state_count()
    assert a.max_depth() == b.max_depth()
    assert sorted(a.discoveries()) == sorted(b.discoveries())


def test_sortmerge_full_capacity_no_load_factor():
    """The visited array works at 100% occupancy — no probe pressure."""
    sm = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=288, frontier_capacity=128, cand_capacity=1024
        )
        .join()
    )
    assert sm.unique_state_count() == 288
    assert sm.metrics["occupancy"] == 1.0


def test_sortmerge_capacity_overflow_detected():
    with pytest.raises(RuntimeError, match="table overflow"):
        (
            TwoPhaseSys(rm_count=3)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=128, frontier_capacity=128, cand_capacity=1024
            )
            .join()
        )


def test_sortmerge_paxos_1client():
    host = (
        paxos_model(PaxosModelCfg(client_count=1, server_count=3))
        .checker()
        .spawn_bfs()
        .join()
    )
    sm = (
        paxos_model(PaxosModelCfg(client_count=1, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            capacity=512, frontier_capacity=128, cand_capacity=2048
        )
        .join()
    )
    assert sm.unique_state_count() == host.unique_state_count() == 265
    assert sorted(sm.discoveries()) == sorted(host.discoveries())


def test_sortmerge_fast_mode_and_targets():
    sm = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .target_max_depth(5)
        .spawn_tpu_sortmerge(
            capacity=512,
            frontier_capacity=128,
            cand_capacity=1024,
            track_paths=False,
        )
        .join()
    )
    ht = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .target_max_depth(5)
        .spawn_tpu(capacity=1 << 10)
        .join()
    )
    assert sm.unique_state_count() == ht.unique_state_count()
    assert sm.max_depth() == 5


@pytest.mark.parametrize("tiles", [2, 4])
def test_sortmerge_tiled_matches_untiled(tiles):
    """The tiled expansion path (frontier split into per-wave tiles)
    produces identical results to tiles=1."""
    base = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=512, frontier_capacity=128, cand_capacity=1024
        )
        .join()
    )
    tiled = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=512,
            frontier_capacity=128,
            cand_capacity=1024,
            tiles=tiles,
        )
        .join()
    )
    assert tiled.unique_state_count() == base.unique_state_count() == 288
    assert tiled.state_count() == base.state_count()
    assert sorted(tiled.discoveries()) == sorted(base.discoveries())
    for name, path in tiled.discoveries().items():
        prop = tiled.model.property_by_name(name)
        assert prop.condition(tiled.model, path.last_state())


def test_discoveries_survive_overflow_raise():
    """A discovery recorded before a capacity-overflow raise stays
    readable through the public accessors, and later accessors replay
    the stored error instead of re-running the whole search (round-5
    review finding: the advertised recovery path was unreachable)."""
    from stateright_tpu.models.increment import Increment

    total = (
        Increment(thread_count=4)
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
    )
    c = Increment(thread_count=4).checker().spawn_tpu_sortmerge(
        capacity=total - 10,
        frontier_capacity=1 << 12,
        cand_capacity=1 << 14,
        track_paths=False,
    )
    with pytest.raises(RuntimeError, match="table overflow"):
        c.join()
    # The 'fin' violation is found long before the visited array fills;
    # the names/fingerprints survive the raise.
    assert "fin" in c.discovered_property_names()
    assert c.discovery_fingerprints()["fin"] != 0
    # Non-discovery accessors replay the SAME error, immediately.
    import time as _time

    t0 = _time.monotonic()
    with pytest.raises(RuntimeError, match="table overflow"):
        c.unique_state_count()
    assert _time.monotonic() - t0 < 1.0


def test_auto_budget_resizes_from_measured_peak(tmp_path, monkeypatch):
    """cand_capacity="auto" (VERDICT r4 item 7): the engine sizes its
    candidate budget from measured wave peaks — a deliberately absurd
    initial guess (forced via a pre-seeded budget store) overflows
    loudly, auto-resizes from the observed peak, re-runs, and persists
    the converged budget for the next process."""
    import json

    from stateright_tpu.checkers import tpu_sortmerge as sm

    store = tmp_path / "budgets.json"
    monkeypatch.setattr(
        sm.SortMergeTpuBfsChecker,
        "_budget_store",
        lambda self: str(store),
    )

    def spawn():
        return (
            TwoPhaseSys(rm_count=5)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << 14,
                frontier_capacity=1 << 11,
                cand_capacity="auto",
                track_paths=False,
            )
        )

    # Pre-seed a hopeless budget so the resize path is exercised.
    c0 = spawn()
    store.write_text(json.dumps({
        c0._budget_key(): {"cand_capacity": 64, "pair_width": None},
    }))
    c = spawn()
    assert c.cand_capacity == 64
    c.join()
    assert c.unique_state_count() == 8832
    assert c.cand_capacity >= c.metrics["max_wave_candidates"]
    saved = json.loads(store.read_text())[c._budget_key()]
    assert saved["cand_capacity"] == c.cand_capacity
    # A fresh checker starts from the persisted converged budget.
    c2 = spawn()
    assert c2.cand_capacity == c.cand_capacity


def test_auto_budget_explicit_pair_width_wins(tmp_path, monkeypatch):
    """cand_capacity="auto" fills pair_width from the store only as a
    DEFAULT: an explicitly passed pair_width must survive (ADVICE r5 —
    the store used to silently overwrite it)."""
    import json

    from stateright_tpu.actor import Network
    from stateright_tpu.actor.compile import compile_actor_model
    from stateright_tpu.checkers import tpu_sortmerge as sm
    from stateright_tpu.models.ping_pong import (
        PingPongCfg,
        ping_pong_model,
    )
    from test_actor_compile import ping_pong_specs

    store = tmp_path / "budgets.json"
    monkeypatch.setattr(
        sm.SortMergeTpuBfsChecker,
        "_budget_store",
        lambda self: str(store),
    )
    cfg = PingPongCfg(max_nat=3)
    model = ping_pong_model(cfg).init_network(
        Network.new_unordered_nonduplicating()
    )
    enc = compile_actor_model(model, **ping_pong_specs(cfg))

    def spawn(**kw):
        return model.checker().spawn_tpu_sortmerge(
            encoded=enc,
            capacity=1 << 10,
            frontier_capacity=1 << 7,
            cand_capacity="auto",
            track_paths=False,
            **kw,
        )

    c0 = spawn()
    assert c0._use_sparse()
    store.write_text(json.dumps({
        c0._budget_key(): {"cand_capacity": 4096, "pair_width": 7},
    }))
    # No explicit pair_width: the persisted value fills the default.
    assert spawn()._pair_width() == 7
    # Explicit pair_width: the constructor argument wins.
    c = spawn(pair_width=3)
    assert c.pair_width == 3
    assert c._pair_width() == 3
    assert c.cand_capacity == 4096  # cand budget still adopted


def test_save_budget_concurrent_writers_keep_all_keys(
    tmp_path, monkeypatch
):
    """The budget store is shared by concurrent checker processes
    writing DIFFERENT keys; the save cycle is serialized on a lock
    file with a re-read before the atomic replace, so no writer drops
    another's entry (ADVICE r5: the unlocked read-modify-write lost
    the race loser's key)."""
    import copy
    import json
    import threading
    import time

    from stateright_tpu.checkers import tpu_sortmerge as sm

    store = tmp_path / "budgets.json"
    monkeypatch.setattr(
        sm.SortMergeTpuBfsChecker,
        "_budget_store",
        lambda self: str(store),
    )
    base = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=512,
            frontier_capacity=128,
            cand_capacity="auto",
            track_paths=False,
        )
    )
    # One checker per simulated process, each saving its own key;
    # widen the read->replace window so an unlocked implementation
    # reliably loses keys.
    real_dump = json.dump

    def slow_dump(*a, **kw):
        time.sleep(0.01)
        return real_dump(*a, **kw)

    monkeypatch.setattr(json, "dump", slow_dump)
    writers = []
    for i in range(8):
        c = copy.copy(base)
        c._budget_key = lambda i=i: f"key-{i}"
        writers.append(c)
    threads = [
        threading.Thread(target=c._save_budget) for c in writers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    data = json.loads(store.read_text())
    assert set(data) == {f"key-{i}" for i in range(8)}


# -- 2pc sparse dispatch (round 6) ----------------------------------------


def test_twopc_sparse_contract_exhaustive():
    """The SparseEncodedModel contract for the 2pc encoding, pinned
    exhaustively over the rm=3 (288) and rm=4 (1,568) spaces:
    ``enabled_bits_vec`` unpacks to ``enabled_mask_vec`` equals
    ``step_vec`` validity on every slot, ``step_slot_vec`` reproduces
    ``step_vec``'s successor on every enabled pair, popcounts agree,
    and ``pair_width_hint`` bounds the true per-row enabled peak."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.models.two_phase_commit_tpu import (
        TwoPhaseSysEncoded,
    )
    from stateright_tpu.ops.bitmask import popcount_words, words_to_mask

    for rm, expected in ((3, 288), (4, 1568)):
        enc = TwoPhaseSysEncoded(rm)
        host = TwoPhaseSys(rm_count=rm).checker().spawn_bfs().join()
        vecs = {}
        from collections import deque

        m = enc.host_model
        q = deque(m.init_states())
        for s in list(q):
            vecs[tuple(enc.encode(s).tolist())] = s
        while q:
            s = q.popleft()
            for a in m.actions(s):
                t = m.next_state(s, a)
                if t is None:
                    continue
                k = tuple(enc.encode(t).tolist())
                if k not in vecs:
                    vecs[k] = t
                    q.append(t)
        assert len(vecs) == expected == host.unique_state_count()
        arr = jnp.asarray(
            __import__("numpy").array(sorted(vecs), dtype="uint32")
        )
        succs, valid = (
            np.asarray(a)
            for a in jax.jit(jax.vmap(enc.step_vec))(arr)
        )
        mask = np.asarray(
            jax.jit(jax.vmap(enc.enabled_mask_vec))(arr)
        )
        assert (mask == valid).all(), f"rm={rm} mask != step validity"
        bits = jnp.asarray(
            np.asarray(jax.jit(jax.vmap(enc.enabled_bits_vec))(arr))
        )
        assert (
            np.asarray(words_to_mask(jnp, bits, enc.max_actions))
            == mask
        ).all()
        assert (
            np.asarray(popcount_words(jnp, bits))
            == mask.sum(axis=1)
        ).all()
        rows, slots = np.nonzero(valid)
        sp = np.asarray(
            jax.jit(jax.vmap(enc.step_slot_vec))(
                arr[jnp.asarray(rows)],
                jnp.asarray(slots.astype(np.uint32)),
            )
        )
        assert (sp == succs[rows, slots]).all(), (
            f"rm={rm} step_slot_vec diverges from step_vec"
        )
        peak = int(valid.sum(axis=1).max())
        assert peak <= enc.pair_width_hint, (peak, enc.pair_width_hint)


def test_twopc_sparse_engine_matches_dense():
    """2pc through SPARSE dispatch (the round-6 default — the encoding
    now implements SparseEncodedModel) produces the identical count,
    discoveries, and replayable paths as the dense wave."""
    dense = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu_sortmerge(
            sparse=False,
            capacity=1 << 12,
            frontier_capacity=512,
            cand_capacity=4096,
        )
        .join()
    )
    sp = (
        TwoPhaseSys(rm_count=4)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 12,
            frontier_capacity=512,
            cand_capacity=4096,
        )
        .join()
    )
    assert sp._use_sparse() and not dense._use_sparse()
    assert (
        sp.unique_state_count()
        == dense.unique_state_count()
        == 1568
    )
    assert sorted(sp.discoveries()) == sorted(dense.discoveries())
    sp.assert_properties()
    for name, path in sp.discoveries().items():
        prop = sp.model.property_by_name(name)
        assert prop.condition(sp.model, path.last_state())


def test_auto_budget_shrinks_oversized_on_clean_run(
    tmp_path, monkeypatch
):
    """Auto-budget shrink (ROADMAP carried item): the store only ever
    GREW, so a lane whose growth heuristic overshot kept its headroom
    forever — the paxos-4 lane converged at 2,097,152 against an
    observed peak of 660,492 (3.2x), silently flipping the
    padded-residency gate into CHUNKED memory-lean mode. A clean run
    with > 2x headroom must persist ``observed_peak * margin``
    instead (the running checker keeps its compiled budget; the next
    process adopts the shrunk one) and emit an ``auto_budget_shrink``
    telemetry event."""
    import json

    from stateright_tpu.checkers import tpu_sortmerge as sm
    from stateright_tpu.telemetry import RunTracer

    store = tmp_path / "budgets.json"
    monkeypatch.setattr(
        sm.SortMergeTpuBfsChecker,
        "_budget_store",
        lambda self: str(store),
    )

    def spawn():
        return (
            TwoPhaseSys(rm_count=5)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << 14,
                frontier_capacity=1 << 11,
                cand_capacity="auto",
                track_paths=False,
            )
        )

    # Pre-seed an absurdly oversized budget: no overflow, huge slack.
    c0 = spawn()
    oversized = 1 << 20
    store.write_text(json.dumps({
        c0._budget_key(): {"cand_capacity": oversized,
                           "pair_width": None},
    }))
    tr = RunTracer()
    c = spawn()
    assert c.cand_capacity == oversized
    with tr.activate():
        c.join()
    assert c.unique_state_count() == 8832
    peak = c.metrics["max_wave_candidates"]
    want = max(
        int(peak * sm.SortMergeTpuBfsChecker._SHRINK_MARGIN), 1024
    )
    saved = json.loads(store.read_text())[c._budget_key()]
    assert saved["cand_capacity"] == want
    assert saved["cand_capacity"] < oversized
    assert saved["cand_capacity"] >= peak
    # the running checker keeps its compiled budget
    assert c.cand_capacity == oversized
    evs = [e for e in tr.events if e["ev"] == "auto_budget_shrink"]
    assert evs and evs[0]["old"] == oversized
    assert evs[0]["new"] == want
    assert evs[0]["observed_peak"] == peak
    # the next process starts from the shrunk budget and stays clean
    c2 = spawn()
    assert c2.cand_capacity == want
    c2.join()
    assert c2.unique_state_count() == 8832
    # near-peak budget: the 2x guard keeps the store stable now
    assert (
        json.loads(store.read_text())[c2._budget_key()][
            "cand_capacity"
        ]
        == want
    )


def test_auto_budget_no_shrink_after_overflow(tmp_path, monkeypatch):
    """The no-shrink-after-overflow contract: a budget grown on THIS
    run is a geometric guess, not a measurement — persisting a shrunk
    value right after the growth would thrash the store (grow 4x,
    shrink, overflow again next process). The grown value must
    survive even when it exceeds the shrink threshold."""
    import json

    from stateright_tpu.checkers import tpu_sortmerge as sm

    store = tmp_path / "budgets.json"
    monkeypatch.setattr(
        sm.SortMergeTpuBfsChecker,
        "_budget_store",
        lambda self: str(store),
    )

    def spawn():
        return (
            TwoPhaseSys(rm_count=5)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << 14,
                frontier_capacity=1 << 11,
                cand_capacity="auto",
                track_paths=False,
            )
        )

    # learn the true peak from one clean run
    probe = spawn()
    probe.join()
    peak = probe.metrics["max_wave_candidates"]
    # seed just under the peak: overflow -> geometric growth to
    # ~3.2x peak, which is PAST the 2x-headroom shrink threshold
    seeded = max(int(peak * 0.8), 16)
    store.write_text(json.dumps({
        probe._budget_key(): {"cand_capacity": seeded,
                              "pair_width": None},
    }))
    c = spawn()
    with pytest.warns(RuntimeWarning, match="auto-budget"):
        c.join()
    assert c.unique_state_count() == 8832
    saved = json.loads(store.read_text())[c._budget_key()]
    # grown, converged, and NOT shrunk on the same run
    assert saved["cand_capacity"] == c.cand_capacity
    assert saved["cand_capacity"] > seeded
    want = max(
        int(peak * sm.SortMergeTpuBfsChecker._SHRINK_MARGIN), 1024
    )
    assert saved["cand_capacity"] > 2 * want, (
        "fixture lost its point: the grown budget must exceed the "
        "shrink threshold for this test to prove suppression"
    )
