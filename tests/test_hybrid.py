"""The hybrid racer (checkers/hybrid.py): host DFS vs the device
engine, first complete run wins, loser cancelled. Shallow-violation
workloads resolve at host speed; the winner's full result surface is
adopted either way."""

from stateright_tpu.models.increment import Increment
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_hybrid_shallow_bug_matches_host():
    host = Increment(thread_count=4).checker().spawn_dfs().join()
    hy = (
        Increment(thread_count=4)
        .checker()
        .spawn_hybrid(
            capacity=1 << 16,
            frontier_capacity=1 << 12,
            cand_capacity=1 << 14,
            track_paths=False,
        )
        .join()
    )
    assert sorted(hy.discoveries() if hy.winner == "host"
                  else hy.discovered_property_names()) == sorted(
        host.discoveries()
    )
    assert hy.winner in ("host", "device")
    # The discovery must be replayable when the host won (the device
    # side ran fingerprint-only here).
    if hy.winner == "host":
        p = hy.discovery("fin")
        assert p is not None and len(p.actions()) >= 1


def test_hybrid_does_not_mask_host_panic():
    """A model error that manifests only on the host (a raising
    actions(), examples/panic.rs semantics — hand encodings never run
    the host enumeration) must surface even when the device engine
    completes and would otherwise claim the win (ADVICE r4)."""
    import pytest

    class PanickingIncrement(Increment):
        def actions(self, state):
            raise RuntimeError("panic! (host-only model error)")

    with pytest.raises(RuntimeError, match="panic|refusing to mask"):
        (
            PanickingIncrement(thread_count=4)
            .checker()
            .spawn_hybrid(
                capacity=1 << 16,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )
            .join()
        )


def test_hybrid_host_oom_emits_structured_event():
    """Host-side MemoryError is the race being LOST, not a model
    error: the device result is adopted with a warning — and, since
    round 12, a STRUCTURED telemetry event (phase + message) so a
    traced run records the outcome in the artifact, not only on
    stderr (the memory-observability satellite)."""
    import warnings

    import pytest

    from stateright_tpu.telemetry import RunTracer, validate_events

    class OomIncrement(Increment):
        def actions(self, state):
            raise MemoryError("host trace tuples exhausted RAM")

    tracer = RunTracer()
    with tracer.activate():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            hy = (
                OomIncrement(thread_count=4)
                .checker()
                .spawn_hybrid(
                    capacity=1 << 16,
                    frontier_capacity=1 << 12,
                    cand_capacity=1 << 14,
                    track_paths=False,
                )
                .join()
            )
    assert hy.winner == "device"
    assert any("ran out of memory" in str(x.message) for x in w)
    validate_events(tracer.events)
    evs = [e for e in tracer.events if e["ev"] == "hybrid_host_oom"]
    assert len(evs) == 1
    assert evs[0]["phase"] == "host_dfs"
    assert "ran out of memory" in evs[0]["message"]
    assert evs[0]["error"].startswith("MemoryError")

    # The existing error path is unchanged: a non-OOM host raise is a
    # model error and must still surface (no masking, no event).
    class PanickingIncrement(Increment):
        def actions(self, state):
            raise RuntimeError("panic! (host-only model error)")

    tracer2 = RunTracer()
    with tracer2.activate():
        with pytest.raises(RuntimeError,
                           match="panic|refusing to mask"):
            (
                PanickingIncrement(thread_count=4)
                .checker()
                .spawn_hybrid(
                    capacity=1 << 16,
                    frontier_capacity=1 << 12,
                    cand_capacity=1 << 14,
                    track_paths=False,
                )
                .join()
            )
    assert not [e for e in tracer2.events
                if e["ev"] == "hybrid_host_oom"]


def test_hybrid_full_verification_matches():
    """Run-to-completion workload: whichever engine wins, the count is
    the pinned 8,832 and the property set matches the host oracle."""
    host = TwoPhaseSys(rm_count=5).checker().spawn_bfs().join()
    hy = (
        TwoPhaseSys(rm_count=5)
        .checker()
        .spawn_hybrid(
            capacity=1 << 14,
            frontier_capacity=1 << 11,
            cand_capacity=1 << 14,
        )
        .join()
    )
    assert hy.unique_state_count() == host.unique_state_count() == 8832
    assert sorted(hy.discoveries()) == sorted(host.discoveries())
    hy.assert_properties()
