"""Live service metrics + SLO layer (ISSUE 19, ROADMAP direction
2(c) signal plane): the streaming metrics registry
(stateright_tpu/metrics.py), the tracer->metrics bridge, the
Prometheus exposition round-trip, rollup JSONL validation through
telemetry's validator, the ONE shared quantile implementation pinned
exact-vs-bucket, bridge reconciliation against the committed
TRACE_r30/r31 service traces, the lock-free ``/.status`` metrics
block under concurrent scrape, the null-path (inactive-registry)
no-allocation regression, tools/slo_report.py exit codes, and the
sustained ramp->spike->drain loadtest smoke on the pinned 2pc lane.

Rides tier-1 (``pytest -m metrics`` runs it standalone)."""

import importlib.util
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from stateright_tpu import metrics as M
from stateright_tpu.metrics import (
    BRIDGE_FAMILIES,
    SECONDS_BUCKETS,
    MetricsRegistry,
    Rollup,
    bridge_events,
    bucket_quantile,
    evaluate_slo,
    load_rollup,
    parse_prometheus,
    quantile,
    slo_observed,
)

pytestmark = pytest.mark.metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    """Import a tools/ script in-process (the subprocess-free idiom:
    the tools return exit codes from main() instead of exiting)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- registry semantics ----------------------------------------------------


def test_registry_counter_gauge_semantics():
    r = MetricsRegistry()
    c = r.counter("stpu_things_total", "things")
    c.inc()
    c.inc(2.5, lane="a")
    c.inc(lane="a")
    assert r.counter_value("stpu_things_total") == 1.0
    assert r.counter_value("stpu_things_total", lane="a") == 3.5
    assert c.total() == 4.5
    # get-or-create: the same family object comes back, help kept
    assert r.counter("stpu_things_total") is c
    g = r.gauge("stpu_depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert r.gauge_value("stpu_depth") == 2.0
    # unobserved label set reads 0/None, never raises
    assert r.counter_value("stpu_things_total", lane="ghost") == 0.0
    assert r.gauge_value("stpu_missing") == 0.0


def test_registry_kind_conflict_raises():
    r = MetricsRegistry()
    r.counter("stpu_x")
    with pytest.raises(ValueError):
        r.gauge("stpu_x")
    with pytest.raises(ValueError):
        r.histogram("stpu_x")


def test_histogram_bucket_edges_sub_ms_and_tail():
    """The fixed log-bucket layout covers the sub-ms dispatch floor
    AND the >60s long-model tail; the overflow bucket catches
    beyond-layout observations without error."""
    assert SECONDS_BUCKETS[0] <= 0.0001
    assert SECONDS_BUCKETS[-1] >= 120.0
    r = MetricsRegistry()
    h = r.histogram("stpu_t_seconds", "t")
    for v in (0.00005, 0.0002, 70.0, 400.0):
        h.observe(v)
    h.observe(None)          # skipped, not an error
    h.observe(float("nan"))  # skipped
    snap = r.snapshot()["stpu_t_seconds"]["values"][0]
    assert snap["count"] == 4
    counts = snap["counts"]
    # one per edge plus the +Inf overflow slot
    assert len(counts) == len(SECONDS_BUCKETS) + 1
    assert counts[0] == 1                      # 0.00005 <= 1e-4
    assert counts[1] == 1                      # 0.0002 <= 2.5e-4
    assert counts[SECONDS_BUCKETS.index(120.0)] == 1   # 70 <= 120
    assert counts[-1] == 1                     # 400 overflows
    assert snap["min"] == pytest.approx(0.00005)
    assert snap["max"] == pytest.approx(400.0)


# -- the ONE shared quantile implementation --------------------------------


def test_quantile_exact_small_n():
    assert quantile([], 0.5) is None
    assert quantile([7.0], 0.99) == 7.0
    assert quantile([1.0, 2.0, 3.0, 4.0], 0.5) == 2.5
    assert quantile([4.0, 1.0, 3.0, 2.0], 0.0) == 1.0
    assert quantile([4.0, 1.0, 3.0, 2.0], 1.0) == 4.0


def test_quantile_pin_exact_vs_bucket_interpolated():
    """The satellite pin: the exact path (serve_report /
    serve_loadtest aggregate rows) and the streaming
    bucket-interpolated path (the live histogram quantile behind
    /.status and the SLO gate) agree on the SAME sample to within one
    bucket's width — the two report paths cannot drift apart."""
    sample = [0.0008, 0.0012, 0.003, 0.004, 0.0041, 0.009, 0.02,
              0.024, 0.09, 0.4]
    r = MetricsRegistry()
    h = r.histogram("stpu_pin_seconds", "pin")
    for v in sample:
        h.observe(v)
    for q in (0.50, 0.90, 0.99):
        exact = quantile(sample, q)
        streamed = h.quantile(q)
        # the streaming answer lands in the same bucket as the exact
        # one: bounded by that bucket's edges
        edges = (0.0,) + SECONDS_BUCKETS
        lo = max(e for e in edges if e <= exact)
        hi = min(e for e in SECONDS_BUCKETS if e >= exact)
        assert lo <= streamed <= hi, (q, exact, streamed)
    # bucket_quantile honors the observed min/max clamp
    counts = r.snapshot()["stpu_pin_seconds"]["values"][0]["counts"]
    assert bucket_quantile(SECONDS_BUCKETS, counts, 0.0,
                           vmin=min(sample), vmax=max(sample)) \
        == pytest.approx(min(sample))
    assert bucket_quantile(SECONDS_BUCKETS, counts, 1.0,
                           vmin=min(sample), vmax=max(sample)) \
        == pytest.approx(max(sample))


# -- Prometheus exposition --------------------------------------------------


def test_prometheus_escaping_and_roundtrip():
    r = MetricsRegistry()
    c = r.counter("stpu_esc_total", 'help with "quotes" and \\slash')
    c.inc(3, lane='say "hi"\nback\\slash')
    h = r.histogram("stpu_esc_seconds", "hist")
    h.observe(0.002, lane="a")
    h.observe(7.0, lane="a")
    text = r.render_prometheus()
    # label escaping: \ -> \\, " -> \", newline -> \n
    assert 'lane="say \\"hi\\"\\nback\\\\slash"' in text
    # histogram exposition: cumulative _bucket series + +Inf + sum/count
    assert 'le="+Inf"' in text
    assert "stpu_esc_seconds_sum" in text
    assert "stpu_esc_seconds_count" in text
    back = parse_prometheus(text)
    assert back["stpu_esc_total"]["values"][0]["value"] == 3.0
    assert (back["stpu_esc_total"]["values"][0]["labels"]["lane"]
            == 'say "hi"\nback\\slash')
    hv = back["stpu_esc_seconds"]["values"][0]
    snap = r.snapshot()["stpu_esc_seconds"]["values"][0]
    # de-cumulated per-bucket counts match the registry snapshot
    assert hv["counts"] == snap["counts"]
    assert hv["count"] == 2
    assert hv["sum"] == pytest.approx(7.002)


# -- the null path (inactive registry) -------------------------------------


def test_null_path_is_one_shared_singleton():
    """The unmetered fast path allocates NO per-call Python objects:
    with no registry active the module-level hooks hand back the ONE
    slot-less no-op singleton, every method swallows args and returns
    None — the engine's hot loops see a constant, not a constructor.
    This is the structural regression behind the PERF.md §metrics
    overhead bar."""
    assert M.active_registry() is None
    assert M.counter("stpu_anything_total") is M._NULL
    assert M.gauge("stpu_anything") is M._NULL
    assert M.histogram("stpu_anything_seconds") is M._NULL
    # same singleton for every name: no per-family allocation either
    assert M.counter("stpu_other_total") is M._NULL
    assert type(M._NULL).__slots__ == ()
    assert M._NULL.inc(1.0, lane="x") is None
    assert M._NULL.observe(0.5) is None
    assert M._NULL.set(1) is None
    assert M._NULL.value() == 0.0
    assert M._NULL.quantile(0.99) is None


def test_activate_scopes_the_module_hooks():
    r = MetricsRegistry()
    with M.activate(r):
        assert M.active_registry() is r
        M.counter("stpu_live_total").inc()
        with pytest.raises(RuntimeError):
            with M.activate(MetricsRegistry()):
                pass
    assert M.active_registry() is None
    assert r.counter_value("stpu_live_total") == 1.0


# -- rollup JSONL rides the telemetry validator ----------------------------


def test_rollup_jsonl_validates_and_loads(tmp_path):
    from stateright_tpu.telemetry import load_trace, validate_events

    r = MetricsRegistry()
    r.counter("stpu_ticks_total").inc(5)
    r.histogram("stpu_tick_seconds").observe(0.01)
    path = str(tmp_path / "metrics.jsonl")
    roll = Rollup(path, 0.05, source=lambda: r).start()
    time.sleep(0.18)
    roll.stop()
    events = load_trace(path)
    validate_events(events)  # metrics_rollup is a schema'd event
    assert all(ev["ev"] == "metrics_rollup" for ev in events)
    assert len(events) >= 2  # ticks plus the final stop() flush
    last = load_rollup(path)
    assert (last["families"]["stpu_ticks_total"]["values"][0]["value"]
            == 5.0)
    # monotone tick stamps
    ts = [ev["t"] for ev in events]
    assert ts == sorted(ts)


def test_rollup_rejects_nonpositive_interval(tmp_path):
    with pytest.raises(ValueError):
        Rollup(str(tmp_path / "m.jsonl"), 0.0,
               source=MetricsRegistry)


def test_load_rollup_requires_a_rollup_event(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps(
        dict(ev="run_begin", run=0, t=0.0, lane={}, schema=1)
    ) + "\n")
    with pytest.raises(ValueError):
        load_rollup(str(p))


# -- the tracer->metrics bridge reconciles with the report tools -----------


@pytest.mark.parametrize("trace", ["TRACE_r30.jsonl",
                                   "TRACE_r31.jsonl"])
def test_bridge_reconciles_with_serve_and_latency_reports(trace):
    """Replay a committed service trace through the bridge and assert
    EXACT agreement with what tools/serve_report.py and
    tools/latency_report.py derive from the same events — one stream,
    two views, zero drift."""
    from stateright_tpu.serve import serve_summary
    from stateright_tpu.telemetry import (
        latency_summary,
        load_trace,
        validate_events,
    )

    events = load_trace(os.path.join(REPO_ROOT, trace))
    validate_events(events)
    reg = bridge_events(events)
    snap = reg.snapshot()
    for fam in snap:
        assert fam in BRIDGE_FAMILIES or fam.startswith("stpu_")

    # raw event-count counters match the stream exactly
    def n(ev):
        return sum(1 for e in events if e.get("ev") == ev)

    assert reg.counter("stpu_waves_total").total() == n("wave")
    assert reg.counter("stpu_chunks_total").total() == n("chunk")
    assert reg.counter("stpu_verdicts_total").total() == n("verdict")
    assert (reg.counter("stpu_program_builds_total").total()
            == n("program_build"))

    summary = serve_summary(events)
    sessions = summary["sessions"]
    # session terminal states, one count each
    by_state = {}
    for s in sessions:
        by_state[s["state"]] = by_state.get(s["state"], 0) + 1
    for state, count in by_state.items():
        assert reg.counter_value("stpu_sessions_total",
                                 state=state) == count

    # time-to-verdict: the bridge's histogram saw EXACTLY the ttv the
    # serve report prints per session (same max-verdict-wall rule)
    ttvs = sorted(s["time_to_verdict_sec"] for s in sessions
                  if s.get("time_to_verdict_sec") is not None)
    fam = snap.get("stpu_time_to_verdict_seconds")
    cell = fam["values"][0]
    assert cell["count"] == len(ttvs)
    assert cell["sum"] == pytest.approx(sum(ttvs), abs=1e-6)
    assert cell["min"] == pytest.approx(ttvs[0], abs=1e-9)
    assert cell["max"] == pytest.approx(ttvs[-1], abs=1e-9)

    # queue wait: bridge sum == the serve report's per-session column
    qw = [s.get("queue_wait_sec") or 0.0 for s in sessions]
    qcell = snap["stpu_queue_wait_seconds"]["values"][0]
    assert qcell["count"] == len(sessions)
    assert qcell["sum"] == pytest.approx(sum(qw), abs=1e-6)

    # compile tiers: bridge labels == the union of the report's
    # per-session builds.tiers
    tiers = {}
    for s in sessions:
        for t, c in ((s.get("builds") or {}).get("tiers")
                     or {}).items():
            tiers[t] = tiers.get(t, 0) + c
    for t, c in tiers.items():
        assert reg.counter_value("stpu_program_builds_total",
                                 tier=t) == c

    # latency view: the last run's verdict count agrees too
    lat = latency_summary(events)
    assert lat is not None
    assert len(lat["verdicts"]) <= reg.counter(
        "stpu_verdicts_total"
    ).total()


# -- SLO spec evaluation ----------------------------------------------------


def _rollup_families():
    r = MetricsRegistry()
    h = r.histogram("stpu_time_to_verdict_seconds")
    for v in (0.5, 1.0, 2.0):
        h.observe(v)
    q = r.histogram("stpu_queue_wait_seconds")
    q.observe(0.01)
    adm = r.counter("stpu_serve_admission_total")
    adm.inc(9, decision="accepted")
    adm.inc(1, decision="refused")
    warm = r.counter("stpu_serve_warm_hits_total")
    warm.inc(3, result="warm")
    warm.inc(1, result="cold")
    return r.snapshot(), r


def test_slo_observed_and_evaluate():
    families, _ = _rollup_families()
    obs = slo_observed(families)
    assert obs["refusal_rate"] == pytest.approx(0.1)
    assert obs["cache_hit_rate"] == pytest.approx(0.75)
    assert obs["ttv_p99_sec"] is not None
    ev = evaluate_slo(
        dict(max_ttv_p99_sec=60.0, max_refusal_rate=0.2,
             min_cache_hit_rate=0.5),
        obs,
    )
    assert ev["ok"] is True
    assert all(o["status"] == "ok" for o in ev["objectives"])
    bad = evaluate_slo(dict(max_refusal_rate=0.05), obs)
    assert bad["ok"] is False
    assert bad["objectives"][0]["status"] == "violated"
    # an unmeasured objective FAILS the gate: silence is never
    # compliance
    unmeasured = evaluate_slo(
        dict(max_queue_wait_p99_sec=1.0),
        slo_observed({}),
    )
    assert unmeasured["ok"] is False
    assert unmeasured["objectives"][0]["status"] == "unmeasured"
    with pytest.raises(ValueError):
        evaluate_slo(dict(max_bogus=1.0), obs)


def test_slo_report_exit_codes(tmp_path, capsys):
    """0 = objectives met, 1 = violated or unmeasured, 2 = bad input
    — the exit code IS the gate."""
    slo_report = _load_tool("slo_report")
    families, reg = _rollup_families()
    rollup = str(tmp_path / "m.jsonl")
    roll = Rollup(rollup, 3600.0, source=lambda: reg).start()
    roll.stop()  # the final flush writes one rollup line

    def run(argv):
        old = sys.argv
        sys.argv = ["slo_report.py"] + argv
        try:
            return slo_report.main()
        finally:
            sys.argv = old

    assert run(["--rollup", rollup, "--max-ttv-p99", "60",
                "--max-refusal-rate", "0.2"]) == 0
    assert run(["--rollup", rollup,
                "--max-refusal-rate", "0.01"]) == 1
    # unmeasured -> 1 as well (the families carry no serve queue hist
    # but DO carry the engine queue fallback; use an absent signal)
    empty = MetricsRegistry()
    empty_rollup = str(tmp_path / "empty.jsonl")
    r2 = Rollup(empty_rollup, 3600.0, source=lambda: empty).start()
    r2.stop()
    assert run(["--rollup", empty_rollup,
                "--max-ttv-p99", "60"]) == 1
    # bad inputs -> 2
    assert run(["--rollup", rollup]) == 2            # empty spec
    assert run(["--rollup", str(tmp_path / "nope.jsonl"),
                "--max-ttv-p99", "60"]) == 2         # unreadable
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"max_bogus": 1.0}))
    assert run(["--rollup", rollup, "--spec", str(spec)]) == 2
    # artifact write: SLO_r* in its own round sequence + provenance
    assert run(["--rollup", rollup, "--max-ttv-p99", "60",
                "--json", "--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "SLO_r01.json" in out
    doc = json.loads((tmp_path / "SLO_r01.json").read_text())
    assert doc["evaluation"]["ok"] is True
    assert "provenance" in doc

    from stateright_tpu.artifacts import latest_slo_summary

    ref = latest_slo_summary(root=str(tmp_path))
    assert ref is not None
    assert ref["artifact"] == "SLO_r01.json"
    assert ref["ok"] is True
    assert ref["objectives"] == {"max_ttv_p99_sec": "ok"}


# -- the lock-free /.status + /.metrics surface ----------------------------


def test_status_metrics_block_answers_concurrently(tmp_path):
    """The compact /.status metrics block and the /.metrics scrape
    keep answering while the dispatch gate is HELD — the same
    answer-while-busy rule the Explorer status poll pins. 8
    concurrent scrapers, zero errors, every response carries the
    block."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.serve import CheckService

    service = CheckService(spool_dir=str(tmp_path))
    service.mount_explorer(TwoPhaseSys(rm_count=2).checker(), "2pc")
    server = service.http_server("127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    service._gate.acquire()  # a session "holds the device"
    try:
        results = []

        def scrape(i):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.status", timeout=10
            ) as r:
                status = json.loads(r.read())
            assert status["model"] == "TwoPhaseSys"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/.metrics", timeout=10
            ) as r:
                text = r.read().decode()
            results.append((status, text))

        threads = [threading.Thread(target=scrape, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        service._gate.release()
        server.shutdown()
    assert len(results) == 8
    for status, text in results:
        block = status["service"]["metrics"]
        assert block["active_sessions"] == 0
        assert block["queue_depth"] == 0
        assert block["refusals"] == 0
        assert block["ttv_p99_sec"] is None
        # the gauges are pre-registered: a fresh scrape already
        # carries the family names, not an empty exposition
        assert "stpu_serve_queue_depth" in text
        assert "stpu_serve_active_sessions" in text
        families = parse_prometheus(text)
        assert (families["stpu_serve_queue_depth"]["values"][0]
                ["value"] == 0.0)


# -- CLI flags --------------------------------------------------------------


def test_cli_pop_metrics_flags():
    from stateright_tpu.cli import _pop_metrics_flags

    interval, path, rest = _pop_metrics_flags(
        ["2pc", "check", "3", "--metrics-interval=2.5",
         "--metrics-path=m.jsonl"]
    )
    assert interval == 2.5
    assert path == "m.jsonl"
    assert rest == ["2pc", "check", "3"]
    assert _pop_metrics_flags(["2pc", "check", "3"]) \
        == (None, None, ["2pc", "check", "3"])
    with pytest.raises(SystemExit):
        _pop_metrics_flags(["--metrics-interval"])
    with pytest.raises(SystemExit):
        _pop_metrics_flags(["--metrics-interval=0"])
    with pytest.raises(SystemExit):
        _pop_metrics_flags(["x", "--metrics-path=m.jsonl"])


# -- the sustained loadtest (the SLO evidence path), smoke-sized ----------


def test_sustained_loadtest_smoke(tmp_path, capsys):
    """ramp(1) -> spike(2) -> drain(1) of the pinned 2pc rm=3 lane
    (288 states) against ONE live service over HTTP: the mid-spike
    /.metrics scrape serves the named families, every served count is
    bit-identical to the solo baseline, the per-phase quantiles come
    out both ways, the SLO gate evaluates, and the SERVE/SLO/TRACE
    artifacts land."""
    loadtest = _load_tool("serve_loadtest")
    code, doc = loadtest.run_sustained(
        ["2pc", "check-tpu", "3"],
        [("ramp", 1), ("spike", 2), ("drain", 1)],
        dict(max_ttv_p99_sec=600.0, max_refusal_rate=0.0),
        json_out=True,
        root=str(tmp_path),
    )
    assert code == 0
    assert doc["solo_unique"] == 288
    assert doc["evaluation"]["ok"] is True
    phases = {p["phase"]: p for p in doc["phases"]}
    assert set(phases) == {"ramp", "spike", "drain"}
    for p in doc["phases"]:
        assert p["sessions"] == p["clients"]
        assert p["ttv_p50_sec"] is not None
        assert p["ttv_p50_bucket_sec"] is not None
    # the /.status block was captured mid-spike
    assert doc["status_metrics"] is not None
    # artifacts: TRACE pair + SERVE with the registry snapshot
    # embedded + the SLO gate doc
    serve = json.loads((tmp_path / "SERVE_r01.json").read_text())
    assert serve["sustained"]["solo_unique"] == 288
    assert "stpu_serve_admission_total" in serve["metrics"]
    slo = json.loads((tmp_path / "SLO_r01.json").read_text())
    assert slo["evaluation"]["ok"] is True
    assert slo["serve_artifact"] == "SERVE_r01.json"
    assert (tmp_path / slo["trace"]).exists()
