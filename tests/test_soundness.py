"""Reduction soundness analyzer gate (``soundness`` marker).

The tentpole contract (analysis/soundness.py): a declared
``DeviceRewriteSpec`` or ample mask is CERTIFIED by static analysis
— no state-space enumeration — before any engine trusts it. The gate
pins:

* the two shipping specs certify — 2pc (symmetry + ample mask, all
  seven obligations) and the N-client register family (symmetry
  only), both with ZERO over-approximated primitives (the bit-level
  abstract interpreter walks their jaxprs exactly);
* the certificate's claims are TRUE on the register family — host
  DFS, host DFS + symmetry, and the device sort-merge engine under
  ``--symmetry`` agree with the closed-form counts (raw
  ``1 + 2n*3^(n-1)``, orbits ``1 + n(n+1)``), and the 2pc device
  counts match the round-20 pinned values;
* three deliberately BROKEN specs refuse with three DISTINCT
  obligations — a non-closed rewrite set (overlapping member fields)
  fails ``group-closure``, a property reading one permuted field
  asymmetrically fails ``property-invariance``, an ample mask
  dropping every member's property-relevant slot fails
  ``ample-non-suppression`` — and the refusal surfaces through the
  REAL engine spawn, not just the analyzer API;
* ``--unsound-ok`` (``CheckerBuilder.unsound_ok()``) waives the gate
  without certifying anything;
* both refusal families — the round-20 capability refusal and the
  certificate refusal — speak through one formatter
  (checkers/common.reduction_refusal);
* the walker the analyzer rides handles ``lax.cond``/``lax.switch``
  branch sub-jaxprs and closed-over constants (satellite edge
  cases);
* a certificate-status flip between two traces of one workload is a
  trace-diff DIVERGENCE (tools/trace_diff.py), and the
  ``SOUND_r*.json`` artifact round-trips through
  ``artifacts.latest_soundness_summary``.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu import telemetry  # noqa: E402
from stateright_tpu.analysis.soundness import (  # noqa: E402
    apply_member_permutation,
    analyze_main,
    certify_encoding,
    gate_ample,
    gate_symmetry,
    soundness_status,
    write_soundness_artifact,
)
from stateright_tpu.analysis.walker import (  # noqa: E402
    SiteWalk,
    iter_eqns,
    source_of,
)
from stateright_tpu.artifacts import latest_soundness_summary  # noqa: E402
from stateright_tpu.checkers.common import (  # noqa: E402
    soundness_refusal,
    symmetry_refusal,
)
from stateright_tpu.models.nclient_register import (  # noqa: E402
    NClientRegSys,
)
from stateright_tpu.models.nclient_register_tpu import (  # noqa: E402
    NClientRegEncoded,
)
from stateright_tpu.models.two_phase_commit import (  # noqa: E402
    TwoPhaseSys,
)
from stateright_tpu.models.two_phase_commit_tpu import (  # noqa: E402
    TwoPhaseSysEncoded,
)
from stateright_tpu.ops.bitmask import pack_bits_host  # noqa: E402
from stateright_tpu.ops.canonical import (  # noqa: E402
    DeviceRewriteSpec,
    MemberField,
)
from stateright_tpu.telemetry import (  # noqa: E402
    RunTracer,
    diff_traces,
    load_trace,
)

pytestmark = pytest.mark.soundness

SYM_OBLIGATIONS = (
    "group-closure",
    "orbit-structure",
    "fingerprint-invariance",
    "property-invariance",
    "transition-equivariance",
)
AMPLE_OBLIGATIONS = ("ample-enabledness", "ample-non-suppression")


# -- the three deliberately broken specs (ISSUE 18 satellite 1) ------------


class Overlap2pc(TwoPhaseSysEncoded):
    """Non-closed rewrite set: two lane-0 member fields whose bit
    ranges OVERLAP (member m's width-2 field at bit 2m and width-1
    field at bit 2m+1 share a bit), so applying two permutations in
    sequence is not the composed permutation — rebuild ORs clobbered
    bits. Structurally valid (each field alone fits its stride);
    only the semantic group-closure check can see it."""

    def device_rewrite_spec(self):
        return DeviceRewriteSpec(
            n_members=self.rm_count,
            fields=(
                MemberField(lane=0, shift=0, stride=2, width=2,
                            sort_key=True),
                MemberField(lane=0, shift=1, stride=2, width=1,
                            sort_key=True),
            ),
        )


class AsymProp(NClientRegEncoded):
    """Property reading a permuted field ASYMMETRICALLY: the extra
    condition looks only at client 0's 4-bit block, so permuting
    clients flips the property verdict between orbit members."""

    def property_conditions_vec(self, vec):
        base = super().property_conditions_vec(vec)
        return base.at[0].set((vec[1] & jnp.uint32(3)) == 2)


class BadAmple(TwoPhaseSysEncoded):
    """Ample mask suppressing an enabled property-relevant action:
    drop slot ``4 + 5*rm`` for EVERY member, so the dropped
    transitions have no symmetric kept image — the reduced graph can
    miss property-relevant successors."""

    def ample_mask_host(self):
        keep = np.ones(self.max_actions, dtype=bool)
        for rm in range(self.rm_count):
            keep[4 + 5 * rm] = False
        return pack_bits_host(keep)


def _failed_rules(res):
    return [f.rule for f in res.obligations if f.severity == "error"]


# -- the shipping specs certify --------------------------------------------


def test_2pc_certifies_all_seven_obligations():
    res = certify_encoding(TwoPhaseSysEncoded(4), use_cache=False)
    assert res.certified
    assert res.sym_certified is True
    assert res.ample_certified is True
    rules = [f.rule for f in res.obligations]
    assert tuple(rules) == SYM_OBLIGATIONS + AMPLE_OBLIGATIONS
    assert all(f.severity == "info" for f in res.obligations)
    # fully precise interpretation: nothing was over-approximated
    assert res.collapsed == []
    assert res.analyzer_sec > 0


def test_register_family_certifies_symmetry():
    res = certify_encoding(NClientRegEncoded(4), use_cache=False)
    assert res.certified
    assert res.sym_certified is True
    assert res.ample_certified is None  # no mask declared
    assert tuple(f.rule for f in res.obligations) == SYM_OBLIGATIONS
    assert res.collapsed == []


def test_soundness_status_views():
    assert soundness_status(NClientRegEncoded(3)) is True
    assert soundness_status(Overlap2pc(3)) is False

    class NoReductions:
        width, max_actions = 1, 1

    assert soundness_status(NoReductions()) is None


def test_apply_member_permutation_matches_encode():
    """The analyzer's group action agrees with the encoding: permuting
    members of an encoded row equals encoding the permuted state."""
    enc = NClientRegEncoded(3)
    spec = enc.device_rewrite_spec()
    model = NClientRegSys(3)
    s = model.init_states()[0]
    for s2 in model.next_states(s):
        s = s2  # a non-trivial state (one client wrote)
        break
    row = enc.encode(s)
    perm = (2, 0, 1)  # output member p takes input member perm[p]
    got = apply_member_permutation(spec, row[None, :], perm)[0]
    from dataclasses import replace

    want = enc.encode(
        replace(s, clients=tuple(s.clients[p] for p in perm))
    )
    assert np.array_equal(got, want)


# -- the certificate's claims are true (pinned counts) ---------------------


def test_register_counts_host_and_device():
    """Closed-form counts, three ways: raw host DFS, host DFS +
    symmetry, device sort-merge + symmetry (n=4: raw 217, orbits 21)."""
    n = 4
    raw = 1 + 2 * n * 3 ** (n - 1)
    orbits = 1 + n * (n + 1)
    assert (raw, orbits) == (217, 21)

    host_raw = NClientRegSys(n).checker().spawn_dfs().join()
    assert host_raw.unique_state_count() == raw

    host_sym = (
        NClientRegSys(n).checker().symmetry().spawn_dfs().join()
    )
    assert host_sym.unique_state_count() == orbits

    dev_sym = (
        NClientRegSys(n)
        .checker()
        .symmetry()
        .spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=128,
            cand_capacity=512, waves_per_sync=2,
        )
        .join()
    )
    assert dev_sym.unique_state_count() == orbits


def test_2pc_device_symmetry_count_unchanged():
    """The certificate gate must not perturb the round-20 pinned
    reduction: 2pc rm=3 under --symmetry still visits exactly 80."""
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .symmetry()
        .spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=128,
            cand_capacity=512, waves_per_sync=2,
        )
        .join()
    )
    assert c.unique_state_count() == 80


# -- the three broken specs refuse, distinctly -----------------------------


def test_overlap_spec_fails_group_closure():
    res = certify_encoding(Overlap2pc(3), use_cache=False)
    assert not res.certified
    assert res.sym_certified is False
    assert res.failed("symmetry").rule == "group-closure"
    # group-closure failing short-circuits the other symmetry checks
    sym_rules = [f.rule for f in res.obligations
                 if f.data.get("scope") == "symmetry"]
    assert sym_rules == ["group-closure"]
    # collateral: the inherited 2pc ample mask loses its symmetric-
    # image argument once the spec is uncertified — also refused
    assert res.ample_certified is False


def test_asym_property_fails_property_invariance():
    res = certify_encoding(AsymProp(4), use_cache=False)
    assert not res.certified
    assert res.sym_certified is False
    assert _failed_rules(res) == ["property-invariance"]


def test_bad_ample_fails_non_suppression_only():
    """The guards of the dropped slots are member-symmetric (every
    member's slot is dropped), so ample-enabledness PASSES — the mask
    fails precisely on non-suppression: an enabled property-relevant
    transition has no symmetric kept image."""
    res = certify_encoding(BadAmple(3), use_cache=False)
    assert not res.certified
    assert res.sym_certified is True  # the spec itself is fine
    assert res.ample_certified is False
    assert _failed_rules(res) == ["ample-non-suppression"]


def test_refusals_are_distinct_and_name_the_obligation():
    msgs = {}
    for enc, scope in (
        (Overlap2pc(3), "symmetry"),
        (AsymProp(4), "symmetry"),
        (BadAmple(3), "ample"),
    ):
        res = certify_encoding(enc)
        f = res.failed(scope)
        msgs[f.rule] = f.message
    assert set(msgs) == {
        "group-closure", "property-invariance",
        "ample-non-suppression",
    }
    assert len(set(msgs.values())) == 3


def test_engine_refuses_overlap_spec_at_spawn():
    with pytest.raises(ValueError, match="group-closure"):
        (
            TwoPhaseSys(rm_count=3)
            .checker()
            .symmetry()
            .spawn_tpu_sortmerge(
                encoded=Overlap2pc(3), capacity=1 << 10,
                frontier_capacity=128, cand_capacity=512,
            )
        )


def test_engine_refuses_asym_property_at_spawn():
    with pytest.raises(ValueError, match="property-invariance"):
        (
            NClientRegSys(4)
            .checker()
            .symmetry()
            .spawn_tpu_sortmerge(
                encoded=AsymProp(4), capacity=1 << 10,
                frontier_capacity=128, cand_capacity=512,
            )
        )


def test_engine_refuses_bad_ample_at_program_build():
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sortmerge(
            encoded=BadAmple(3), capacity=1 << 10,
            frontier_capacity=128, cand_capacity=2048,
            ample_set=True,
        )
    )
    with pytest.raises(ValueError, match="ample-non-suppression"):
        c.join()


# -- the --unsound-ok escape hatch -----------------------------------------


def test_unsound_ok_waives_both_gates():
    assert gate_symmetry(Overlap2pc(3), "spawn_x",
                         unsound_ok=True) is False
    assert gate_ample(BadAmple(3), "spawn_x",
                      unsound_ok=True) is False
    # certified specs gate True regardless
    assert gate_symmetry(NClientRegEncoded(4), "spawn_x") is True


def test_unsound_ok_builder_spawns_uncertified_spec():
    """``CheckerBuilder.unsound_ok()`` reaches the spawn gate: the
    overlap spec that refuses above constructs without raising."""
    c = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .unsound_ok()
        .symmetry()
        .spawn_tpu_sortmerge(
            encoded=Overlap2pc(3), capacity=1 << 10,
            frontier_capacity=128, cand_capacity=512,
        )
    )
    assert c.unsound_ok is True
    assert c.sym_spec is not None


# -- one refusal formatter (satellite 2) -----------------------------------


def test_refusal_families_share_the_formatter():
    head = "symmetry reduction: spawn_x cannot honor it"
    cap = str(symmetry_refusal("spawn_x", missing="a spec"))
    cert = str(soundness_refusal(
        "spawn_x", "symmetry", "group-closure", "not a group"
    ))
    assert cap.startswith(head)
    assert cert.startswith(head)
    assert "missing capability" in cap
    assert "obligation 'group-closure' failed" in cert
    assert "--unsound-ok" in cert
    amp = str(soundness_refusal(
        "spawn_x", "ample-set", "ample-enabledness", "d"
    ))
    assert amp.startswith(
        "ample-set reduction: spawn_x cannot honor it"
    )


# -- walker edge cases (satellite 3) ---------------------------------------


def test_walker_enters_cond_branches():
    def f(x):
        return jax.lax.cond(
            x[0] > 0, lambda v: v + 1, lambda v: v * 2, x
        )

    closed = jax.make_jaxpr(f)(np.zeros(2, np.int32))
    walk = SiteWalk(closed)
    assert any(s.primitive == "cond" for s in walk)
    sub = [s for s in walk
           if s.stack and s.stack[-1][0] == "cond"]
    # both branch bodies walked, branch index recorded on the stack
    assert {s.stack[-1][1] for s in sub} == {0, 1}
    assert any(s.in_branch() for s in walk)
    for s in sub:
        assert isinstance(source_of(s.eqn), str)


def test_walker_enters_all_switch_branches():
    def f(x):
        branches = [
            lambda v: v + 1,
            lambda v: v * 2,
            lambda v: v - 3,
        ]
        return jax.lax.switch(x[0], branches, x)

    closed = jax.make_jaxpr(f)(np.zeros(2, np.int32))
    sites = list(iter_eqns(closed.jaxpr))
    sub = [s for s in sites
           if s.stack and s.stack[-1][0] == "cond"]
    assert {s.stack[-1][1] for s in sub} == {0, 1, 2}
    # each branch sub-jaxpr is distinct and owns its equations
    assert len({id(s.jaxpr) for s in sub}) == 3


def test_walker_closed_over_constants():
    table = np.arange(1, 5, dtype=np.int32)

    def f(x):
        return x * jnp.asarray(table)

    closed = jax.make_jaxpr(f)(np.zeros(4, np.int32))
    assert len(closed.consts) == 1
    assert np.array_equal(np.asarray(closed.consts[0]), table)
    # constvars are real Vars (the analyzer keys env by id, and the
    # literal test is the absence of .count)
    assert all(hasattr(v, "count") for v in closed.jaxpr.constvars)
    walk = SiteWalk(closed)
    assert any(s.primitive == "mul" for s in walk)


def test_analyzer_interprets_cond_exactly():
    """An encoding-shaped fn with a data-dependent ``lax.cond`` still
    interprets without collapse when both branches are bit-tractable:
    certify the register spec against a property that routes through
    cond (the interpreter joins the branches with the pred's deps)."""

    class CondProp(NClientRegEncoded):
        def property_conditions_vec(self, vec):
            base = super().property_conditions_vec(vec)
            # pred reads the (unpermuted) register lane; both branch
            # values are whole-lane facts, invariant under permuting
            # the client blocks
            extra = jax.lax.cond(
                (vec[0] & jnp.uint32(1)) != 0,
                lambda v: (v[0] | v[1]) != jnp.uint32(0),
                lambda v: v[1] == v[1],
                vec,
            )
            return jnp.concatenate([base, extra[None]])

    res = certify_encoding(CondProp(3), use_cache=False)
    # both branches are symmetric in the clients, so it certifies
    assert res.sym_certified is True


# -- certificate flip is a trace divergence (satellite 5) ------------------


def _cert_trace(tmp_path, name, certified):
    tr = RunTracer()
    with tr.activate():
        tr.begin_run(lane=dict(
            engine="T", soundness_certified=certified,
        ))
        with telemetry.span("compile"):
            pass
        tr.record_chunk(
            chunk=0, wave0=0, t0=0.0, t1=1.0,
            dispatch_sec=0.01, fetch_sec=0.5,
            wave_rows=np.array([[4, 6, 5, 4, 5, 1, 0, 0]]),
        )
        tr.end_run(error=None, total_states=4, unique_states=5,
                   max_depth=1, duration_sec=1.0)
    path = str(tmp_path / name)
    tr.write_jsonl(path)
    return load_trace(path)


def test_cert_status_flip_diffs_as_divergence(tmp_path):
    a = _cert_trace(tmp_path, "a.jsonl", True)
    b = _cert_trace(tmp_path, "b.jsonl", False)
    same = diff_traces(a, _cert_trace(tmp_path, "a2.jsonl", True))
    assert same["ok"]
    rep = diff_traces(a, b)
    assert not rep["ok"]
    flips = [d for d in rep["divergences"]
             if d["field"] == "soundness_certified"]
    assert flips and flips[0]["a"] is True and flips[0]["b"] is False


# -- artifact + CLI (satellites 4/5) ---------------------------------------


def test_sound_artifact_roundtrip(tmp_path):
    root = str(tmp_path)
    res = certify_encoding(NClientRegEncoded(4))
    path = write_soundness_artifact([res], root=root)
    assert os.path.basename(path) == "SOUND_r01.json"
    with open(path) as fh:
        report = json.load(fh)
    assert report["schema"] == "soundness-cert/v1"
    assert report["clean"] is True
    (spec_dict,) = report["specs"].values()
    assert spec_dict["status"] == "certified"
    assert spec_dict["collapsed_primitives"] == []

    summary = latest_soundness_summary(root)
    assert summary is not None
    assert summary["clean"] is True
    assert set(summary["specs"].values()) == {"certified"}

    # own round sequence: the next write is r02
    path2 = write_soundness_artifact([res], root=root)
    assert os.path.basename(path2) == "SOUND_r02.json"
    assert os.path.basename(
        latest_soundness_summary(root)["artifact"]
    ) == "SOUND_r02.json"


def test_refused_spec_marks_artifact_dirty(tmp_path):
    root = str(tmp_path)
    res = certify_encoding(Overlap2pc(3))
    write_soundness_artifact([res], root=root)
    summary = latest_soundness_summary(root)
    assert summary["clean"] is False
    assert set(summary["specs"].values()) == {"refused"}


def test_analyze_cli_smoke(capsys, monkeypatch, tmp_path):
    monkeypatch.chdir(tmp_path)  # keep any artifact out of the repo
    assert analyze_main(
        ["soundness", "register", "3", "--no-artifact"]
    ) == 0
    out = capsys.readouterr().out
    assert "certified" in out
    assert "ok  group-closure" in out

    assert analyze_main(["soundness", "no-such-model"]) == 2
    assert analyze_main([]) == 2


def test_committed_certificate_is_current():
    """The repo-root SOUND artifact (satellite 5) exists, is clean,
    and certifies both shipping targets."""
    summary = latest_soundness_summary()
    assert summary is not None, "no SOUND_r*.json committed"
    assert summary["clean"] is True
    names = " ".join(summary["specs"])
    assert "TwoPhaseSysEncoded" in names
    assert "NClientRegEncoded" in names
