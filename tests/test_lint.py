"""The kernel-lint gate (``pytest -m lint``, round 7).

Two halves:

* the GATE — the full rule registry over every registered encoding ×
  both sparse engine pipelines plus the wave-body fixture must come
  back clean (the same run ``tools/lint_kernels.py`` exits 0 on);
* the TEETH — deliberate regressions (re-densified enabled mask, a
  mask-path table gather, ``[N, 1]`` lane math, a stepped-up gather
  count, a branch that pads its class result to peak shape) must each
  be caught by the NAMED rule with a source-attributed finding.

The teeth tests are what make the gate trustworthy: a lint that
passes clean code but misses the priced artifacts would let the next
encoding refactor silently re-grow the 8x/82x taxes the rules pin.
"""

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.analysis import (  # noqa: E402
    ENCODINGS,
    EncodingSpec,
    RULES,
    TraceCtx,
    lint_encoding,
    lint_wave_body,
    run_lint,
    run_rules,
)
from stateright_tpu.models.two_phase_commit_tpu import (  # noqa: E402
    TwoPhaseSysEncoded,
)

pytestmark = pytest.mark.lint


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


def _spec(cls, max_step_gathers=0):
    return EncodingSpec(
        name=cls.__name__,
        kind="hand",
        factory=lambda: cls(4),
        max_step_gathers=max_step_gathers,
    )


# -- the gate --------------------------------------------------------------

def test_lint_clean_all_registered():
    """Every registered encoding × both engine pipelines × the
    wave-body fixture: zero error-severity findings. This is the
    tier-1 codegen-contract gate."""
    report = run_lint()
    errors = [
        f for f in report["findings"] if f["severity"] == "error"
    ]
    assert report["clean"], errors
    # Coverage: every registered encoding traced on every path the
    # contract names, for both engines, in BOTH pipeline shapes —
    # the small-wave variant AND the production compaction/tiled-mask
    # branches (review finding: the compaction path the bench lanes
    # actually run was previously never audited).
    covered = {(p["encoding"], p["path"]) for p in report["paths"]}
    for spec in ENCODINGS:
        for path in ("bits", "bits[t]", "mask", "step",
                     "step[t]", "step[t1]",
                     "engine:single", "engine:single+compact",
                     "engine:sharded", "engine:sharded+compact"):
            # bits[t]/step[t] are the transposed [W, N] invocations
            # (round 9, registry.TRANSPOSED_PATHS) — every encoding
            # must be audited in both invocation styles.
            assert (spec.name, path) in covered, (spec.name, path)
    assert any(p["path"] == "wave-body" for p in report["paths"])
    # the sharded engine's TRACED wave body (round 11: the per-shard
    # mesh-log path) is part of the default gate
    from stateright_tpu.analysis.registry import (
        SHARDED_WAVE_BODY_FIXTURE,
    )

    assert (SHARDED_WAVE_BODY_FIXTURE, "wave-body") in covered


def test_lint_registry_names_all_rules():
    names = {r.name for r in RULES}
    assert names == {
        "no-dense-mask", "no-mask-gather", "allowed-table-gather",
        "no-lane-padded-alu", "no-branch-pad-concat",
        "carry-copy-bytes",
    }


def test_wave_body_estimator_emits_and_meets_budget():
    """The carry-copy-bytes estimator prices the class-ladder switch
    on the wave-body fixture, and since round 9 the fixture is GATED:
    the measured switch-carry total must sit under its byte budget
    (tables.CARRY_COPY_BYTE_BUDGETS — the static pin on the round-9
    class collapse, PERF.md §layout)."""
    from stateright_tpu.analysis.tables import CARRY_COPY_BYTE_BUDGETS

    findings, stats = lint_wave_body()
    assert not _errors(findings)
    est = [f for f in findings
           if f.rule == "carry-copy-bytes" and f.severity == "info"]
    assert len(est) == 1
    data = est[0].data
    assert data["switches"] > 0
    assert data["switch_carry_bytes"] > 0
    assert est[0].source  # attributed to the engine source line
    # The fixture is budgeted, and the budget has teeth: the measured
    # value is under it, but NOT by an order of magnitude (a budget
    # 10x above the measurement would let the collapse regress half
    # way back before failing).
    budget = CARRY_COPY_BYTE_BUDGETS[est[0].encoding]
    assert data["budget_bytes"] == budget
    assert data["switch_carry_bytes"] <= budget
    assert budget < 2 * data["switch_carry_bytes"]


def test_sharded_wave_body_traced_and_meets_budget():
    """The SHARDED engine's wave body, in its TRACED form (round 11):
    the per-shard mesh-log path (slog/swave) is registered with the
    lint — zero gated-rule errors, and the switch-carry total sits
    under its own budget (tables.CARRY_COPY_BYTE_BUDGETS) with the
    same has-teeth margin as the single-chip fixture."""
    from stateright_tpu.analysis.lint import lint_sharded_wave_body
    from stateright_tpu.analysis.registry import (
        SHARDED_WAVE_BODY_FIXTURE,
    )
    from stateright_tpu.analysis.tables import CARRY_COPY_BYTE_BUDGETS

    findings, stats = lint_sharded_wave_body()
    assert not _errors(findings)
    est = [f for f in findings
           if f.rule == "carry-copy-bytes" and f.severity == "info"]
    assert len(est) == 1
    assert est[0].encoding == SHARDED_WAVE_BODY_FIXTURE
    data = est[0].data
    assert data["switches"] > 0
    budget = CARRY_COPY_BYTE_BUDGETS[SHARDED_WAVE_BODY_FIXTURE]
    assert data["budget_bytes"] == budget
    assert data["switch_carry_bytes"] <= budget
    assert budget < 2 * data["switch_carry_bytes"]


def test_lint_catches_carry_copy_budget_regression():
    """Deliberate regression: a wave body whose switches carry more
    bytes than the fixture budget must fail the gated rule with an
    error naming both numbers (the pre-round-9 pattern — full carry
    tuples crossing every class-ladder boundary)."""
    from jax import lax

    from stateright_tpu.analysis.tables import CARRY_COPY_BYTE_BUDGETS

    fixture = "engine-fixture(2pc-rm3)"
    budget = CARRY_COPY_BYTE_BUDGETS[fixture]
    # One switch whose branches return a carry fatter than the whole
    # budget (the estimator sums cond outvar bytes).
    rows = (budget // 4) + 1024

    def fat_switch(i, carry):
        def br(c):
            return dict(c, buf=c["buf"] + jnp.uint32(1))

        return lax.switch(i, [br, br], carry)

    ctx = TraceCtx(
        path="wave-body", encoding=fixture, n=64, k=0,
        sparse=False, allow_gathers=None, check_lane_alu=False,
        check_branches=True,
    )
    jx = jax.make_jaxpr(fat_switch)(
        jnp.int32(0), dict(buf=jnp.zeros(rows, jnp.uint32))
    )
    hits = [
        f for f in _errors(run_rules(ctx, jx))
        if f.rule == "carry-copy-bytes"
    ]
    assert hits, "over-budget switch carry not gated"
    assert hits[0].data["switch_carry_bytes"] > budget
    assert str(budget) in hits[0].message.replace(",", "")


# -- the teeth -------------------------------------------------------------

class _DensifiedMask(TwoPhaseSysEncoded):
    """Regression fixture: rebuilds the enabled words by materializing
    the dense bool[K] validity row first (exactly the [F, K] pass the
    82x ablation removed)."""

    def enabled_bits_vec(self, vec):
        from stateright_tpu.ops.bitmask import mask_to_words

        _, valid = self.step_vec(vec)  # dense bool[K]
        return mask_to_words(jnp, valid)


class _GatherMask(TwoPhaseSysEncoded):
    """Regression fixture: a per-state table gather on the mask path
    (the compiled-codegen tax PR 1 removed)."""

    def enabled_bits_vec(self, vec):
        tbl = jnp.arange(8, dtype=jnp.uint32)
        return super().enabled_bits_vec(vec) | tbl[vec[0] % 8][None]


class _LanePaddedStep(TwoPhaseSysEncoded):
    """Regression fixture: [1]-shaped word math on the step path —
    [N, 1] ALU under vmap, the 128x tile-padding artifact."""

    def step_slot_vec(self, vec, slot):
        out = super().step_slot_vec(vec, slot)
        bump = slot.reshape(1) & jnp.uint32(0)  # [1]-shaped `and`
        return out.at[:1].set(out[:1] | bump)


class _TableStep(TwoPhaseSysEncoded):
    """Regression fixture: two per-slot table gathers on a step path
    whose allowance is one."""

    def step_slot_vec(self, vec, slot):
        t1 = jnp.arange(32, dtype=jnp.uint32)
        t2 = jnp.arange(64, dtype=jnp.uint32)
        extra = (t1[slot % 32] & jnp.uint32(0)) | (
            t2[slot % 64] & jnp.uint32(0)
        )
        return super().step_slot_vec(vec, slot) | extra


def test_lint_catches_dense_mask_regression():
    findings, _ = lint_encoding(
        _spec(_DensifiedMask), engines=("single",)
    )
    hits = [
        f for f in _errors(findings) if f.rule == "no-dense-mask"
    ]
    assert hits, _errors(findings)
    # Source-attributed to the traced encoding line, not the walker.
    assert any(
        "two_phase_commit_tpu" in (f.source or "")
        or "test_lint" in (f.source or "")
        for f in hits
    ), [f.source for f in hits]
    # And it leaks into the engine pipeline audit too: the engine
    # consumes the words, so the dense pass rides in.
    assert any(f.path in ("bits", "engine:single") for f in hits)


def test_lint_catches_mask_gather_regression():
    findings, _ = lint_encoding(
        _spec(_GatherMask), engines=("single",)
    )
    hits = [
        f for f in _errors(findings) if f.rule == "no-mask-gather"
    ]
    assert hits, _errors(findings)
    assert all(f.source for f in hits)


def test_lint_catches_lane_padded_alu_regression():
    findings, _ = lint_encoding(
        _spec(_LanePaddedStep), engines=("single",)
    )
    hits = [
        f
        for f in _errors(findings)
        if f.rule == "no-lane-padded-alu" and f.path == "step"
    ]
    assert hits, _errors(findings)


def test_lint_catches_table_gather_overflow():
    findings, _ = lint_encoding(
        _spec(_TableStep, max_step_gathers=1), engines=("single",)
    )
    hits = [
        f
        for f in _errors(findings)
        if f.rule == "allowed-table-gather"
    ]
    assert hits, _errors(findings)
    assert hits[0].data["gathers"] > hits[0].data["allowance"]


def test_lint_step_gather_at_zero_allowance_names_table_rule():
    """A gather on a ZERO-allowance step path (hand 2pc: pure slot
    arithmetic) reports under allowed-table-gather with the
    table-row diagnosis — not under no-mask-gather with a mask-path
    message (review finding: the wrong rule name sends the
    maintainer to the wrong contract)."""
    findings, _ = lint_encoding(
        _spec(_TableStep, max_step_gathers=0), engines=("single",)
    )
    step_hits = [f for f in _errors(findings) if f.path == "step"]
    rules = {f.rule for f in step_hits}
    assert "allowed-table-gather" in rules, step_hits
    assert "no-mask-gather" not in rules, step_hits


def test_lint_catches_branch_pad_concat():
    """The pre-round-6 carry pattern — a switch branch returning its
    class result padded to peak shape — is caught in both forms
    (concat-with-zeros and jnp.pad), while class-local
    dynamic_update_slice branches pass."""
    from jax import lax

    F, W = 512, 4

    def concat_form(i, carry, rows):
        def br_good(c):
            return dict(
                c,
                frontier=lax.dynamic_update_slice(
                    c["frontier"], rows, (0, 0)
                ),
            )

        def br_bad(c):
            full = jnp.concatenate(
                [rows * 2, jnp.zeros((F - 8, W), jnp.uint32)], axis=0
            )
            return dict(c, frontier=full)

        return lax.switch(i, [br_good, br_bad], carry)

    def pad_form(i, carry, rows):
        def br(c):
            return dict(c, frontier=jnp.pad(rows, ((0, F - 8), (0, 0))))

        return lax.switch(i, [br, br], carry)

    ctx = TraceCtx(
        path="wave-body", encoding="synthetic", n=64, k=0,
        sparse=False, allow_gathers=None, check_lane_alu=False,
        check_branches=True,
    )
    carry = dict(frontier=jnp.zeros((F, W), jnp.uint32))
    rows = jnp.ones((8, W), jnp.uint32)
    for form, prim in ((concat_form, "concatenate"), (pad_form, "pad")):
        jx = jax.make_jaxpr(form)(jnp.int32(0), carry, rows)
        hits = [
            f
            for f in _errors(run_rules(ctx, jx))
            if f.rule == "no-branch-pad-concat"
        ]
        assert hits and hits[0].primitive == prim, (form, hits)
        assert "[1]" in hits[0].message or "cond" in hits[0].message


def test_lint_catches_branch_pad_through_passthrough():
    """The branch rule follows value-preserving unary ops: a padded
    carry laundered through `.astype(...)`/reshape before the branch
    return is still caught (review finding: a single convert between
    the concat and the returned carry must not bypass the rule)."""
    from jax import lax

    F, W = 512, 4

    def laundered(i, carry, rows):
        def br(c):
            full = jnp.concatenate(
                [rows * 2, jnp.zeros((F - 8, W), jnp.int32)], axis=0
            )
            # convert + reshape between the rebuild and the return
            return dict(
                c,
                frontier=full.astype(jnp.uint32).reshape(F, W),
            )

        return lax.switch(i, [br, br], carry)

    ctx = TraceCtx(
        path="wave-body", encoding="synthetic", n=64, k=0,
        sparse=False, allow_gathers=None, check_lane_alu=False,
        check_branches=True,
    )
    carry = dict(frontier=jnp.zeros((F, W), jnp.uint32))
    jx = jax.make_jaxpr(laundered)(
        jnp.int32(0), carry, jnp.ones((8, W), jnp.int32)
    )
    hits = [
        f
        for f in _errors(run_rules(ctx, jx))
        if f.rule == "no-branch-pad-concat"
    ]
    assert hits, "passthrough chain hid the peak-shape rebuild"


def test_lint_records_dense_rule_skip_when_ev_equals_k():
    """When an encoding's pair width EV == K the engine-path
    dense-mask rule cannot run (the [N, EV] pair grid is
    shape-identical to the dense mask) — the report must record the
    skip as an info finding, not a silent '0 errors' (review
    finding: coverage claims must be honest). The registered
    compiled ping-pong encoding is exactly this case."""
    from stateright_tpu.analysis import get_encoding_spec
    from stateright_tpu.analysis.lint import engine_pair_width

    spec = get_encoding_spec("compiled-ping-pong-nondup")
    enc = spec.factory()
    assert engine_pair_width(enc) == enc.max_actions  # the edge
    findings, _ = lint_encoding(spec, engines=("single",))
    skips = [
        f
        for f in findings
        if f.severity == "info"
        and f.rule == "no-dense-mask"
        and f.path == "engine:single"
    ]
    assert skips and "SKIPPED" in skips[0].message


def test_lint_report_shape():
    """The --json artifact contract: rules, paths, findings, clean."""
    report = run_lint(
        encodings=(_spec(_GatherMask),),
        engines=("single",),
        wave_body=False,
    )
    assert report["clean"] is False
    assert {r["name"] for r in report["rules"]} == {
        r.name for r in RULES
    }
    bad = [f for f in report["findings"] if f["severity"] == "error"]
    assert bad and all(
        {"rule", "encoding", "path", "message"} <= set(f) for f in bad
    )


# -- the symmetry-canonicalization kernel paths (registry.CANONICAL_PATHS) --


def test_lint_covers_canonical_paths_when_spec_declared():
    """An encoding with a ``DeviceRewriteSpec`` is traced on all
    three canonicalization invocations (row-major, transposed [W, N],
    shard_map) — and one without a spec traces NONE of them: the
    audit gates on the same capability probe the engines use
    (encoding.device_rewrite_spec), so a newly symmetric encoding is
    audited the moment the engines would canonicalize it."""
    from stateright_tpu.analysis import get_encoding_spec
    from stateright_tpu.analysis.registry import CANONICAL_PATHS

    _, stats = lint_encoding(
        get_encoding_spec("hand-2pc-rm4"), engines=("single",)
    )
    covered = {s["path"] for s in stats}
    assert set(CANONICAL_PATHS) <= covered, covered
    # and clean: the shipped kernel is gather-free by construction
    findings, _ = lint_encoding(
        get_encoding_spec("hand-2pc-rm4"), engines=("single",)
    )
    assert not [
        f for f in _errors(findings) if f.path in CANONICAL_PATHS
    ]

    _, stats = lint_encoding(
        get_encoding_spec("hand-paxos-2c3s"), engines=("single",)
    )
    assert not (set(CANONICAL_PATHS)
                & {s["path"] for s in stats})


def test_lint_catches_canonical_gather_regression():
    """The TEETH for the canon paths: the obvious alternative
    canonicalizer — extract per-member keys, ``argsort``, permute the
    members back with ``take_along_axis`` — is gather-based, which is
    exactly the priced artifact the shipped kernel avoids (rank via
    comparison counts + one-hot select-sums, ops/canonical.py). Run
    under the canon-path TraceCtx it must be caught by the NAMED
    no-mask-gather rule with a source-attributed finding."""
    enc = TwoPhaseSysEncoded(4)
    spec = enc.device_rewrite_spec()
    f0 = spec.fields[0]
    n = 64

    def gather_canon(states_t):
        lane = states_t[f0.lane]
        fmask = jnp.uint32((1 << f0.width) - 1)
        keys = jnp.stack([
            (lane >> jnp.uint32(f0.shift + m * f0.stride)) & fmask
            for m in range(spec.n_members)
        ])  # [M, N]
        order = jnp.argsort(keys, axis=0)
        skeys = jnp.take_along_axis(keys, order, axis=0)
        out = lane & ~jnp.uint32(
            ((1 << (f0.width * spec.n_members)) - 1) << f0.shift
        )
        for m in range(spec.n_members):
            out = out | (
                skeys[m] << jnp.uint32(f0.shift + m * f0.stride)
            )
        return states_t.at[f0.lane].set(out)

    closed = jax.make_jaxpr(gather_canon)(
        jnp.zeros((enc.width, n), jnp.uint32)
    )
    ctx = TraceCtx(
        path="canon[t]", encoding="gather-canon-fixture", n=n,
        k=enc.max_actions, sparse=True, allow_gathers=0,
        check_lane_alu=True,
    )
    hits = [
        f for f in _errors(run_rules(ctx, closed))
        if f.rule == "no-mask-gather"
    ]
    assert hits, run_rules(ctx, closed)
    assert all(f.source for f in hits)
