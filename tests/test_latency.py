"""Latency-observability gate (``pytest -m latency``).

Covers the round-14 tentpole surface end to end on CPU:

* the compile-cache ledger — a traced engine run emits schema-valid
  ``program_build`` events at the ``_programs`` seam and the lazy
  compile sites, warm in-process fetches tier as ``in_process``, and
  the tiers/walls land in the run-end ``latency_profile``;
* the verdict timeline — one ``verdict`` event per property on both
  the device engines (settle wave/depth from the chunk stats) and the
  host checkers (``_discover`` + the run-end exhaustion sweep), with
  tracing never changing the explored counts;
* the latency differ behind tools/trace_diff.py — deliberate
  regressions (an injected host stall at the chunk-sync readback, a
  forced cold compile via a cache-key perturbation) are each caught
  by the latency alignment and attributed to the RIGHT bucket, while
  pre-round-14 baseline traces skip the block entirely;
* tools/latency_report.py — exit codes, the LAT_r* artifact's own
  round sequence, and the derived-summary round trip.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu import telemetry  # noqa: E402
from stateright_tpu.checkers.tpu_sortmerge import (  # noqa: E402
    SortMergeTpuBfsChecker,
)
from stateright_tpu.models.two_phase_commit import TwoPhaseSys  # noqa: E402
from stateright_tpu.telemetry import (  # noqa: E402
    BUILD_TIERS,
    RunTracer,
    diff_traces,
    format_diff,
    latency_summary,
    load_trace,
    validate_events,
    write_artifacts,
    write_latency_artifact,
)

pytestmark = pytest.mark.latency

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CAPS = dict(capacity=1 << 10, frontier_capacity=256,
             cand_capacity=1024, track_paths=False)


def _spawn(**kw):
    cfg = dict(_CAPS, **kw)
    return TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(**cfg)


def _trace_run(spawn, runs=1):
    tr = RunTracer()
    checkers = []
    with tr.activate():
        for _ in range(runs):
            checkers.append(spawn().join())
    validate_events(tr.events)
    return tr, checkers


# -- compile-cache ledger -------------------------------------------------


def test_traced_run_emits_latency_layer():
    """The tentpole smoke: a traced run carries the full latency
    layer — ledger rows with valid tiers, one verdict per property,
    and the run-end profile — at UNCHANGED exploration counts."""
    c0 = _spawn().join()
    tr, (c1, c2) = _trace_run(_spawn, runs=2)
    assert c1.unique_state_count() == c0.unique_state_count() == 288
    assert c2.state_count() == c0.state_count()

    builds = [e for e in tr.events if e["ev"] == "program_build"]
    assert builds, "a traced run must emit compile-cache ledger rows"
    assert all(b["tier"] in BUILD_TIERS for b in builds)
    assert all(b["wall_sec"] >= 0 for b in builds)
    # run 1 fetched the programs warm: the in_process tier at the
    # _programs seam is the warm-start attribution BENCH_r06 reads
    r1 = [b for b in builds if b["run"] == 1]
    assert any(b["program"] == "programs"
               and b["tier"] == "in_process" for b in r1)
    # ledger keys pair the runs to the SAME compiled program
    keys = {b.get("key") for b in builds}
    assert len(keys) == 1 and None not in keys

    props = {p.name: p for p in c1.model.properties()}
    for run in (0, 1):
        verdicts = [e for e in tr.events
                    if e["ev"] == "verdict" and e["run"] == run]
        assert {v["property"] for v in verdicts} == set(props)
        for v in verdicts:
            exp = props[v["property"]].expectation.name.lower()
            assert v["expectation"] == exp
            # 2pc: both sometimes-properties discover, the always
            # property settles by exhaustion
            assert v["kind"] == (
                "exhaustion" if exp == "always" else "discovery"
            )
            assert v["depth"] >= 1

    profs = [e for e in tr.events if e["ev"] == "latency_profile"]
    assert [p["run"] for p in profs] == [0, 1]
    for p in profs:
        assert p["chunks"] >= 1 and p["waves"] == 11
        assert p["dispatch_net_sec"] <= p["dispatch_sec"] + 1e-9
        assert p["fetch_min_sec"] <= p["fetch_sec"] + 1e-9
        assert 0 <= p["sync_share"] <= 1
        assert p["compile"]["builds"]
    # the warm run's ledger shows no cold wall
    assert profs[1]["compile"]["cold_sec"] == 0.0


def test_untraced_run_has_no_events_but_keeps_accounting():
    """Untraced runs emit nothing — and still expose the host-side
    dispatch/sync split (the bench.py seam) for free."""
    c = _spawn().join()
    lat = c.latency_accounting()
    assert lat is not None and lat["chunks"] >= 1
    assert lat["fetch_sec"] >= 0 and lat["dispatch_sec"] > 0
    assert lat["time_to_first_wave_sec"] > 0


def test_host_checker_verdict_timeline():
    """The host BFS settles its sometimes-properties by discovery
    (with the BFS depth) and the holding always-property by
    exhaustion at run end — all inside one trace run."""
    tr = RunTracer()
    with tr.activate():
        c = TwoPhaseSys(rm_count=2).checker().spawn_bfs().join()
    validate_events(tr.events)
    verdicts = {e["property"]: e for e in tr.events
                if e["ev"] == "verdict"}
    assert set(verdicts) == {p.name for p in c.model.properties()}
    assert verdicts["consistent"]["kind"] == "exhaustion"
    assert verdicts["commit agreement"]["kind"] == "discovery"
    assert verdicts["commit agreement"]["depth"] >= 1
    # discoveries settle before the exhaustion sweep
    assert (verdicts["commit agreement"]["t"]
            <= verdicts["consistent"]["t"])
    # host runs have no chunks: no latency_profile, and that's valid
    assert not [e for e in tr.events
                if e["ev"] == "latency_profile"]


def test_simulation_discovery_verdicts():
    """The simulation engines settle properties too: a traced random
    walk's discovery emits its verdict (with the walk depth), and the
    run-end sweep covers the rest — no engine is outside the
    one-verdict-per-property contract."""
    from stateright_tpu.fixtures import BinaryClock

    tr = RunTracer()
    with tr.activate():
        c = BinaryClock().checker().spawn_simulation(seed=1).join()
    validate_events(tr.events)
    verdicts = {e["property"]: e for e in tr.events
                if e["ev"] == "verdict"}
    assert set(verdicts) == {p.name for p in c.model.properties()}
    assert verdicts["can be zero"]["kind"] == "discovery"
    assert verdicts["in bounds"]["kind"] == "exhaustion"


def test_on_demand_run_to_completion_brackets_verdicts():
    """The on-demand checker bypasses the base ``_ensure_run``; its
    exhaustive pass must still open its own trace run and settle
    every property inside it (the Explorer's run-to-completion path —
    direction 4's metered service is backed by exactly this
    engine)."""
    tr = RunTracer()
    with tr.activate():
        c = TwoPhaseSys(rm_count=2).checker().spawn_on_demand()
        c.run_to_completion()
    validate_events(tr.events)
    runs = {e["run"] for e in tr.events if e["ev"] == "run_begin"}
    assert runs == {0}
    verdicts = [e for e in tr.events if e["ev"] == "verdict"]
    assert {v["property"] for v in verdicts} == {
        p.name for p in c.model.properties()
    }
    assert all(v["run"] == 0 for v in verdicts)
    kinds = {v["property"]: v["kind"] for v in verdicts}
    assert kinds["consistent"] == "exhaustion"
    assert [e for e in tr.events if e["ev"] == "run_end"]


def test_cancelled_run_emits_no_exhaustion_verdicts():
    """A cancelled run (the hybrid racer's losing side) returns early
    with PARTIAL results — it has not exhausted anything, so the
    run-end sweep must stay silent rather than falsely settling
    undiscovered properties."""
    import threading

    tr = RunTracer()
    with tr.activate():
        c = _spawn()
        c.cancel_event = threading.Event()
        c.cancel_event.set()
        c.join()
    assert c.cancelled
    assert not [e for e in tr.events if e["ev"] == "verdict"]


def test_chrome_trace_has_sync_counter_and_verdict_instants(tmp_path):
    tr, _ = _trace_run(_spawn)
    path = tr.write_chrome_trace(str(tmp_path / "t.trace.json"))
    ct = json.load(open(path))
    names = [e.get("name") for e in ct["traceEvents"]]
    assert "host_blocked_ms" in names
    assert any(str(n).startswith("verdict ") for n in names)


# -- derived summary / LAT artifacts / report CLI -------------------------


def test_latency_summary_and_artifact(tmp_path):
    tr, _ = _trace_run(_spawn)
    s = latency_summary(tr.events)
    assert s is not None and s["profile"] is not None
    assert s["builds"] and s["verdicts"]
    assert all(v["t_since_run"] >= 0 for v in s["verdicts"])
    path = write_latency_artifact(
        dict(s, trace="TRACE_rXX.jsonl"), root=str(tmp_path)
    )
    assert os.path.basename(path) == "LAT_r01.json"
    doc = json.load(open(path))
    assert doc["trace"] == "TRACE_rXX.jsonl"
    assert doc["provenance"]["backend"] == "cpu"
    # own round sequence: the next LAT lands at r02 regardless of
    # other artifact families in the root
    path2 = write_latency_artifact(dict(s), root=str(tmp_path))
    assert os.path.basename(path2) == "LAT_r02.json"


def test_latency_report_cli(tmp_path):
    tr, _ = _trace_run(_spawn)
    jsonl, _ = write_artifacts(tr, root=str(tmp_path))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "latency_report.py"),
         jsonl, "--json", "--root", str(tmp_path)],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "compile-cache ledger" in out.stdout
    assert "sync floor" in out.stdout
    assert "time to verdict" in out.stdout
    assert os.path.exists(tmp_path / "LAT_r01.json")

    # a trace without latency events (host-only run pre-dating the
    # layer, synthesized) exits 2
    old = RunTracer()
    with old.activate():
        old.begin_run(lane=dict(engine="X"))
        old.end_run()
    # strip the round-14 events a real end_run no longer adds for
    # chunkless runs (none here), then drop verdicts if any
    bare = [e for e in old.events
            if e["ev"] in ("run_begin", "run_end")]
    p = tmp_path / "bare.jsonl"
    with open(p, "w") as fh:
        for e in bare:
            fh.write(json.dumps(e) + "\n")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "latency_report.py"),
         str(p)],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 2
    assert "no latency events" in out.stderr

    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "latency_report.py"),
         os.path.join(REPO_ROOT, "ROADMAP.md")],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 2


def test_pre_round14_baseline_skips_latency_block():
    """Committed pre-round-14 traces keep diffing: no latency events
    on either side means the block is empty and the verdict is
    unaffected (the compatibility contract)."""
    path = os.path.join(REPO_ROOT, "TRACE_r07.jsonl")
    events = load_trace(path)
    validate_events(events)
    report = diff_traces(events, events)
    assert report["ok"]
    assert report["latency"]["lanes"] == {}
    assert report["latency"]["divergences"] == []


# -- deliberate regressions: caught by the NAMED bucket -------------------


class _SlowStats:
    """Wraps a chunk's stats handle so the blocking readback
    (``np.asarray`` → ``__array__``) pays an injected host stall —
    a real sync-floor regression at the real seam."""

    def __init__(self, inner, stall_sec):
        self._inner = inner
        self._stall = stall_sec

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._stall)
        a = np.asarray(self._inner)
        return a.astype(dtype) if dtype is not None else a


class _StallChecker(SortMergeTpuBfsChecker):
    STALL_SEC = 0.12

    def _lookup_programs(self, n0):
        seed_fn, chunk_fn = super()._lookup_programs(n0)

        def slow_chunk(carry):
            out = chunk_fn(carry)
            return (out[0], _SlowStats(out[1], self.STALL_SEC),
                    *out[2:])

        return seed_fn, slow_chunk


def test_injected_sync_stall_attributed_to_fetch():
    """A host stall injected at the chunk-sync readback must be
    caught by trace_diff's latency alignment and attributed to the
    sync-floor bucket (``fetch_sec``) — with ZERO counter
    divergence, because the stall changes nothing about
    exploration."""
    tr_a, (ca,) = _trace_run(_spawn)

    # warm the stall class's TRACED program cache first (its cache
    # key differs from _spawn's by checker type): the B side must
    # differ from a warm baseline by ONLY the injected stall, not by
    # a fresh build's residual dispatch overhead
    with RunTracer().activate():
        _StallChecker(TwoPhaseSys(rm_count=3).checker(),
                      **_CAPS).join()
    tr_b = RunTracer()
    with tr_b.activate():
        cb = _StallChecker(
            TwoPhaseSys(rm_count=3).checker(), **_CAPS
        ).join()
    validate_events(tr_b.events)
    assert cb.unique_state_count() == ca.unique_state_count()

    report = diff_traces(tr_a.events, tr_b.events)
    assert report["divergences"] == []
    assert not report["ok"]
    assert "fetch_sec" in report["latency"]["regressions"]
    assert "REGRESSION" in format_diff(report)
    # the bucket is RIGHT: dispatch (net of compile) did not flag
    assert "dispatch_net_sec" not in report["latency"]["regressions"]
    # the stall also shows in the engine's untraced accounting
    assert cb.latency_accounting()["fetch_sec"] >= \
        _StallChecker.STALL_SEC


def test_forced_cold_compile_attributed_to_compile():
    """A cache-key perturbation (a waves_per_sync the program cache
    has never seen — time-salted so the persistent XLA disk cache
    can't have it either) forces a genuinely cold compile; the diff
    must attribute the regression to the compile lanes, not to
    dispatch, again at zero counter divergence."""
    # warm side: second run of the standard config (in-process fetch)
    tr_a, (ca,) = _trace_run(_spawn)

    # counts are invariant to waves_per_sync (it only sets the sync
    # cadence); the salt exists purely to defeat the PERSISTENT XLA
    # disk cache across test sessions — it must be wide enough that
    # no earlier session compiled this loop bound (a 16-value salt
    # collided within a day of development)
    wps = 100 + (os.getpid() ^ (time.time_ns() // 1000)) % 4000
    tr_b = RunTracer()
    with tr_b.activate():
        cb = _spawn(waves_per_sync=wps).join()
    validate_events(tr_b.events)
    assert cb.unique_state_count() == ca.unique_state_count()

    builds_b = [e for e in tr_b.events if e["ev"] == "program_build"]
    # the cold compile lands at the FIRST seam to need the program —
    # the memory-analysis AOT pass when traced (the chunk dispatch
    # then loads the executable from the XLA disk cache); what
    # matters is that SOME ledger row carries the real cold wall
    assert any(b["tier"] == "cold" and (b["cold_sec"] or 0) > 0.3
               for b in builds_b), builds_b

    report = diff_traces(tr_a.events, tr_b.events)
    assert report["divergences"] == []
    assert not report["ok"]
    assert "compile_cold_sec" in report["latency"]["regressions"]
    assert "compile_total_sec" in report["latency"]["regressions"]
    # attributed to compile, NOT to dispatch: the subtraction of
    # ledger-attributed compile walls is what keeps this lane quiet
    assert "dispatch_net_sec" not in report["latency"]["regressions"]


def test_verdict_kind_flip_is_divergence():
    """Two runs that settle a property differently (discovery vs
    exhaustion) are not a timing delta — the latency alignment
    reports a divergence and fails the gate."""
    tr_a, _ = _trace_run(_spawn)
    events_b = []
    for e in tr_a.events:
        e = dict(e)
        if e["ev"] == "verdict" and e["property"] == "consistent":
            e["kind"] = "discovery"
        events_b.append(e)
    report = diff_traces(tr_a.events, events_b)
    assert not report["ok"]
    assert any(d["field"] == "verdict_kind"
               and d["property"] == "consistent"
               for d in report["latency"]["divergences"])
    assert "verdict divergence" in format_diff(report)
