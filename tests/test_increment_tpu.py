"""Increment / increment-lock on the TPU engines.

Note on counts: models whose every property gets discovered (racy
increment's "fin" counterexample) early-exit — the reference's racing
workers make visited counts nondeterministic there too (bfs.rs:128-135)
— so those cases compare discovered-property sets, not counts. The
lock-guarded model explores its full space and pins counts exactly.
"""

import numpy as np
import pytest

from stateright_tpu.models.increment import Increment, IncrementLock


def test_increment_lock_full_space_matches_host():
    host = IncrementLock(3).checker().spawn_bfs().join()
    tpu = (
        IncrementLock(3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=256, cand_capacity=1024
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert host.discoveries() == {} and tpu.discoveries() == {}
    tpu.assert_properties()


def test_increment_racy_finds_lost_update():
    host = Increment(3).checker().spawn_bfs().join()
    tpu = (
        Increment(3)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 10, frontier_capacity=256, cand_capacity=1024
        )
        .join()
    )
    assert sorted(tpu.discoveries()) == sorted(host.discoveries()) == ["fin"]
    # The counterexample replays and genuinely violates the invariant.
    path = tpu.assert_any_discovery("fin")
    final = path.last_state()
    assert sum(1 for p in final.s if p.pc >= 3) != final.i


def test_increment_step_exhaustive_differential():
    """Every reachable state's successor set matches the host model."""
    import jax
    import jax.numpy as jnp
    from collections import deque

    m = Increment(3)
    enc = m.to_encoded()
    step = jax.jit(enc.step_vec)
    seen = set()
    frontier = deque()
    for s in m.init_states():
        seen.add(tuple(enc.encode(s).tolist()))
        frontier.append(s)
    while frontier:
        s = frontier.popleft()
        succs, valid = step(jnp.asarray(enc.encode(s)))
        succs, valid = np.asarray(succs), np.asarray(valid)
        dev = sorted(
            tuple(succs[i].tolist())
            for i in range(enc.max_actions)
            if valid[i]
        )
        host = sorted(tuple(enc.encode(n).tolist()) for n in m.next_states(s))
        assert dev == host, f"divergence at {s!r}"
        for n in m.next_states(s):
            key = tuple(enc.encode(n).tolist())
            if key not in seen:
                seen.add(key)
                frontier.append(n)


def test_increment_encode_decode_roundtrip():
    m = IncrementLock(4)
    enc = m.to_encoded()
    for s in m.init_states():
        for n in m.next_states(s):
            assert enc.decode(enc.encode(n)) == n
