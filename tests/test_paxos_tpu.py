"""Paxos on the TPU wave engine, differentially validated.

The north-star workload (BASELINE.json): the full actor-model state —
server protocol state, clients, unordered-nonduplicating network, and
the in-state linearizability tester — encoded to 7 uint32 lanes
(models/paxos_tpu.py), reproducing the reference-pinned 16,668 unique
states for 2 clients / 3 servers (examples/paxos.rs:325, 349) with the
identical discovered-property set.
"""

import numpy as np
import pytest

from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
from stateright_tpu.models.paxos_tpu import PaxosEncoded


@pytest.fixture(scope="module")
def enc1():
    return PaxosEncoded(PaxosModelCfg(client_count=1, server_count=3))


def test_encode_init_roundtrips(enc1):
    model = enc1.host_model
    for s in model.init_states():
        vec = enc1.encode(s)
        assert vec.shape == (enc1.width,)
        # Both Put bits set, nothing else in the network lanes.
        bits = 0
        for ln in range(enc1.net_lanes):
            bits += bin(int(vec[enc1.S + 1 + ln])).count("1")
        assert bits == enc1.C


def test_step_matches_host_successors_1client(enc1):
    """Exhaustive per-state differential: the vectorized step produces
    exactly the encodings of the host model's successors."""
    import jax
    import jax.numpy as jnp
    from collections import deque

    model = enc1.host_model
    step = jax.jit(enc1.step_vec)
    seen = set()
    frontier = deque()
    for s in model.init_states():
        seen.add(tuple(enc1.encode(s).tolist()))
        frontier.append(s)
    checked = 0
    while frontier:
        s = frontier.popleft()
        checked += 1
        succs, valid = step(jnp.asarray(enc1.encode(s)))
        succs, valid = np.asarray(succs), np.asarray(valid)
        dev = sorted(
            tuple(succs[i].tolist()) for i in range(enc1.K) if valid[i]
        )
        host_next = list(model.next_states(s))
        host = sorted(tuple(enc1.encode(n).tolist()) for n in host_next)
        assert dev == host, f"divergence at state {s!r}"
        for n in host_next:
            key = tuple(enc1.encode(n).tolist())
            if key not in seen:
                seen.add(key)
                frontier.append(n)
    assert len(seen) == 265  # host-oracle count for 1c/3s


def test_paxos_1client_tpu_engine(enc1):
    model = paxos_model(PaxosModelCfg(client_count=1, server_count=3))
    host = model.checker().spawn_bfs().join()
    tpu = (
        paxos_model(PaxosModelCfg(client_count=1, server_count=3))
        .checker()
        .spawn_tpu(capacity=1 << 10, frontier_capacity=128)
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count() == 265
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()


def test_lin_table_matches_serializer():
    """The device truth table is built by the real serializer; check a
    few hand-reasoned entries."""
    enc = PaxosEncoded(PaxosModelCfg(client_count=2, server_count=3))
    t = enc._lin_table

    def idx(p3, r3, p4, r4):
        return ((p3 * 3 + r3) * 4 + p4) * 3 + r4

    # Both writes in flight: trivially linearizable.
    assert t[idx(0, 0, 0, 0)]
    # c3 wrote 'A' and read 'A' back: linearizable.
    assert t[idx(3, 1, 0, 0)]
    # c3 read 'B' while only its own 'A' completed and c4's 'B' is
    # still in flight: W_B may linearize before the read — OK.
    assert t[idx(3, 2, 0, 0)]
    # c3 read '\x00' after its own completed write: NOT linearizable
    # (the write precedes the read in program order).
    assert not t[idx(3, 0, 0, 0)]


@pytest.mark.slow
def test_paxos_2clients_16668_tpu():
    """The reference-pinned count (examples/paxos.rs:325, 349) on the
    wave engine, with the host oracle's property set."""
    model = paxos_model(PaxosModelCfg(client_count=2, server_count=3))
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 16,
            frontier_capacity=1 << 12,
            cand_capacity=1 << 14,
            track_paths=False,
        )
        .join()
    )
    assert tpu.unique_state_count() == 16668
    tpu.assert_properties()
    assert tpu.discovered_property_names() == {"value chosen"}


def test_paxos_3clients_depth_differential():
    """The generalized encoding (VERDICT r2 #3): `paxos check 3` on the
    TPU engine matches host BFS state-for-state at bounded depths (the
    full 1,194,428-state space is exercised on real hardware by
    bench.py; the host oracle cannot reach it in test time)."""
    cfg = PaxosModelCfg(client_count=3, server_count=3)
    host = (
        paxos_model(cfg).checker().target_max_depth(7).spawn_bfs().join()
    )
    tpu = (
        paxos_model(cfg)
        .checker()
        .target_max_depth(7)
        .spawn_tpu_sortmerge(
            capacity=1 << 12,
            frontier_capacity=1 << 10,
            cand_capacity=1 << 12,
            track_paths=False,
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.discovered_property_names() == set(host.discoveries())


def test_paxos_4clients_universe_covers_shallow_space():
    """client_count=4 (two proposals on leader 0, two-lane prepares):
    every reachable host state at depth <= 6 encodes inside the bounded
    universe, and the ballot closure brute-force admits the 3-leader
    coexistence patterns the pairwise round-2 rule got wrong."""
    from collections import deque

    from stateright_tpu.models.paxos_tpu import PaxosEncoded

    cfg = PaxosModelCfg(client_count=4, server_count=3)
    enc = PaxosEncoded(cfg)
    assert enc.two_lane
    model = paxos_model(cfg)
    [init] = model.init_states()
    seen = {init: 0}
    q = deque([init])
    while q:
        st = q.popleft()
        d = seen[st]
        enc.encode(st)  # raises if outside the universe
        if d >= 6:
            continue
        for a in model.actions(st):
            ns = model.next_state(st, a)
            if ns is not None and ns not in seen:
                seen[ns] = d + 1
                q.append(ns)
    assert len(seen) > 500


def test_paxos_coexistence_admits_same_round_pairs_with_3_leaders():
    """(2,l1) and (2,l2) CAN coexist when a third leader supplies the
    round-1 support — the 3-leader case the two-leader pair rule
    excluded; and (3,l1)/(3,l2) cannot (only one leader remains for
    rounds 1 and 2)."""
    from stateright_tpu.models.paxos_tpu import PaxosEncoded

    cfg = PaxosModelCfg(client_count=3, server_count=3)
    enc = PaxosEncoded(cfg)
    b = enc.ballot_enum
    from stateright_tpu.actor import Id

    b2l1 = b[(2, Id(1))]
    b2l2 = b[(2, Id(2))]
    b3l1 = b[(3, Id(1))]
    b3l2 = b[(3, Id(2))]
    # Reconstruct coexistence from the la_universe closure: ballot x's
    # prepared messages may carry last-accepted entries from exactly
    # the coexisting lower ballots.
    las_of_b2l2 = enc.la_universe[b2l2]
    assert any(
        1 + (b2l1 - 1) * enc.P <= la < 1 + b2l1 * enc.P
        for la in las_of_b2l2
    )
    las_of_b3l2 = enc.la_universe[b3l2]
    assert not any(
        1 + (b3l1 - 1) * enc.P <= la < 1 + b3l1 * enc.P
        for la in las_of_b3l2
    )


def _reachable_vecs(enc):
    """All encoded reachable states of enc's host model (host BFS)."""
    from collections import deque

    model = enc.host_model
    seen = {}
    q = deque()
    for s in model.init_states():
        key = tuple(enc.encode(s).tolist())
        if key not in seen:
            seen[key] = s
            q.append(s)
    while q:
        s = q.popleft()
        for n in model.next_states(s):
            key = tuple(enc.encode(n).tolist())
            if key not in seen:
                seen[key] = n
                q.append(n)
    return np.array(sorted(seen), dtype=np.uint32)


@pytest.mark.parametrize("clients", [1, 2])
def test_sparse_contract_exhaustive(clients):
    """The SparseEncodedModel contract, pinned exhaustively over the
    full reachable space (1c: 265 states, 2c: 16,668):
    ``enabled_mask_vec`` equals ``step_vec`` validity on every slot,
    and ``step_slot_vec`` reproduces ``step_vec``'s successor on every
    enabled (state, slot) pair."""
    import jax
    import jax.numpy as jnp

    enc = PaxosEncoded(
        PaxosModelCfg(client_count=clients, server_count=3)
    )
    vecs = jnp.asarray(_reachable_vecs(enc))
    n = vecs.shape[0]
    succs, valid = (
        np.asarray(a) for a in jax.jit(jax.vmap(enc.step_vec))(vecs)
    )
    mask = np.asarray(jax.jit(jax.vmap(enc.enabled_mask_vec))(vecs))
    assert (mask == valid).all(), "enabled mask diverges from step_vec"

    rows, slots = np.nonzero(valid)
    sp = np.asarray(
        jax.jit(jax.vmap(enc.step_slot_vec))(
            vecs[jnp.asarray(rows)],
            jnp.asarray(slots.astype(np.uint32)),
        )
    )
    assert (sp == succs[rows, slots]).all(), (
        "step_slot_vec diverges from step_vec"
    )
    assert n == (265 if clients == 1 else 16668)


def test_sparse_engine_paxos1_with_paths():
    """Sparse dispatch end-to-end on the engine, with path replay (the
    differential that the sparse transition agrees with the host)."""
    model = paxos_model(PaxosModelCfg(client_count=1, server_count=3))
    sp = (
        model.checker()
        .spawn_tpu_sortmerge(
            sparse=True,
            pair_width=16,
            capacity=1 << 10,
            frontier_capacity=1 << 7,
            cand_capacity=1 << 9,
        )
        .join()
    )
    assert sp.unique_state_count() == 265
    sp.assert_properties()
    p = sp.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1


@pytest.mark.slow
def test_sparse_engine_paxos2_16668():
    """The pinned 2-client space through sparse dispatch: identical
    count and property set as the dense engines."""
    model = paxos_model(PaxosModelCfg(client_count=2, server_count=3))
    sp = (
        model.checker()
        .spawn_tpu_sortmerge(
            sparse=True,
            pair_width=32,
            capacity=1 << 15,
            frontier_capacity=1 << 12,
            cand_capacity=1 << 13,
            track_paths=False,
        )
        .join()
    )
    assert sp.unique_state_count() == 16668
    sp.assert_properties()
    assert sp.discovered_property_names() == {"value chosen"}


def test_sparse_chunked_mode_matches():
    """The memory-lean chunked sparse path (successors fingerprinted in
    chunks, winners recomputed at fetch) — forced via a tiny flat
    budget — matches the host count with replayable paths."""
    model = paxos_model(PaxosModelCfg(client_count=1, server_count=3))
    sp = (
        model.checker()
        .spawn_tpu_sortmerge(
            sparse=True,
            pair_width=16,
            flat_budget_bytes=1 << 10,
            capacity=1 << 10,
            frontier_capacity=1 << 7,
            cand_capacity=1 << 9,
        )
        .join()
    )
    assert sp.unique_state_count() == 265
    sp.assert_properties()
    p = sp.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1


def test_paxos_4clients_depth_differential():
    """`paxos check 4` — the north-star workload — on the sparse engine
    matches host BFS state-for-state at bounded depth (the full
    2,372,188-state space runs on real hardware via bench.py's
    paxos 4c/3s lane; first executed round 4)."""
    cfg = PaxosModelCfg(client_count=4, server_count=3)
    host = (
        paxos_model(cfg).checker().target_max_depth(9).spawn_bfs().join()
    )
    sp = (
        paxos_model(cfg)
        .checker()
        .target_max_depth(9)
        .spawn_tpu_sortmerge(
            sparse=True,
            pair_width=16,
            capacity=1 << 16,
            frontier_capacity=1 << 15,
            cand_capacity=1 << 16,
            track_paths=False,
        )
        .join()
    )
    assert sp.unique_state_count() == host.unique_state_count() == 8352
    assert sp.discovered_property_names() == set(host.discoveries())


def test_paxos_5clients_depth_differential():
    """client_count=5 (two client lanes, VERDICT r3 #6): the sparse
    engine matches host BFS state-for-state at bounded depth. The
    mask/step_slot contract is additionally pinned exhaustively at
    d<=6 by the round-4 probe (2,188 states, exact)."""
    cfg = PaxosModelCfg(client_count=5, server_count=3)
    enc = PaxosEncoded(cfg)
    assert enc.n_client_lanes == 2 and enc.two_lane
    host = (
        paxos_model(cfg).checker().target_max_depth(6).spawn_bfs().join()
    )
    dev = (
        paxos_model(cfg)
        .checker()
        .target_max_depth(6)
        .spawn_tpu_sortmerge(
            sparse=True,
            pair_width=16,
            capacity=1 << 14,
            frontier_capacity=1 << 13,
            cand_capacity=1 << 14,
            track_paths=False,
        )
        .join()
    )
    assert dev.unique_state_count() == host.unique_state_count()
    assert dev.discovered_property_names() == set(host.discoveries())


@pytest.mark.slow
@pytest.mark.skipif(
    "STPU_EXHAUSTIVE" not in __import__("os").environ,
    reason="~55 min host DFS; run with STPU_EXHAUSTIVE=1 "
    "(verified 2026-07-31: 1,194,428 in 3,275.5s)",
)
def test_paxos_3clients_exhaustive_host_pin():
    """Independent exhaustive verification of the README-headline
    count: host DFS explores the full 3-client space with no device
    involvement and must report exactly 1,194,428 unique states with
    only 'value chosen' discovered (VERDICT r3 weak #4)."""
    ck = (
        paxos_model(PaxosModelCfg(client_count=3, server_count=3))
        .checker()
        .spawn_dfs()
        .join()
    )
    assert ck.unique_state_count() == 1194428
    assert sorted(ck.discoveries()) == ["value chosen"]
