"""The streaming-merge dedup gate (round 10, ops/merge.py + the
incrementally-sorted visited invariant in both sort-merge engines).

Runs in tier-1 (`-m 'not slow'`); ``pytest -m merge`` runs it
standalone. Covers, per the PR contract:

* randomized property tests for both implementations (XLA fallback
  and the Pallas kernel under ``interpret=True`` — the CPU gate for
  the kernel itself): sorted×sorted → sorted, dup-mask parity against
  the retired rebuild-sort oracle, all-sentinel tails, 2-limb tie
  handling, empty-run edges, and non-default block sizes (partition
  edges);
* end-to-end count/path parity of the engines under every
  ``merge_impl``;
* the steady-state wave-body jaxpr audit: no ``lax.sort`` anywhere in
  the wave program touches O(C) rows (the b·V re-sort the round-10
  rework deletes — the acceptance criterion's "no O(C)-row sort op").
"""

import numpy as np
import pytest

pytestmark = pytest.mark.merge

SENT = 0xFFFFFFFF

IMPLS = ("xla", "pallas_interpret")


def _keys(vals64):
    vals64 = np.asarray(vals64, np.uint64)
    return (
        (vals64 & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        (vals64 >> np.uint64(32)).astype(np.uint32),
    )


def _u64(lo, hi):
    return (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(
        lo, np.uint64
    )


def _sorted_with_tail(rng, n_real, total, pool):
    """Sorted real keys + all-ones sentinel tail up to a FIXED total
    length — the engines' visited layout. Fixed shapes keep the jit
    cache warm across randomized trials (sizes vary via the real
    prefix, not the array shape)."""
    vals = np.sort(rng.choice(pool, size=n_real, replace=True))
    vals = np.concatenate(
        [vals, np.full(total - n_real, np.uint64(0xFFFFFFFFFFFFFFFF))]
    )
    return _keys(vals)


def _tie_pool(rng, n):
    """Keys engineered to collide per limb: shared hi limbs with
    distinct lo limbs AND shared lo limbs with distinct hi limbs, so
    a compare that drops either limb (or orders them wrongly) fails."""
    hi = rng.integers(0, 4, size=n, dtype=np.uint64)
    lo = rng.integers(0, 4, size=n, dtype=np.uint64)
    return (hi << np.uint64(32)) | lo


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("block", [32, 512])
def test_merge_sorted_randomized(impl, block):
    import jax.numpy as jnp

    from stateright_tpu.ops.merge import merge_sorted

    rng = np.random.default_rng(7)
    for trial in range(8):
        pool = _tie_pool(rng, 64)
        na, nb = int(rng.integers(0, 300)), int(rng.integers(0, 120))
        a_lo, a_hi = _sorted_with_tail(rng, na, 320, pool)
        b_lo, b_hi = _sorted_with_tail(rng, nb, 140, pool)
        m_lo, m_hi = merge_sorted(
            jnp.asarray(a_lo), jnp.asarray(a_hi),
            jnp.asarray(b_lo), jnp.asarray(b_hi),
            impl=impl, block=block,
        )
        got = _u64(np.asarray(m_lo), np.asarray(m_hi))
        want = np.sort(
            np.concatenate([_u64(a_lo, a_hi), _u64(b_lo, b_hi)]),
            kind="stable",
        )
        assert (got == want).all(), trial


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("block", [32, 512])
def test_member_sorted_randomized(impl, block):
    import jax.numpy as jnp

    from stateright_tpu.ops.merge import member_sorted

    rng = np.random.default_rng(11)
    for trial in range(8):
        pool = _tie_pool(rng, 48)
        na, nq = int(rng.integers(0, 300)), int(rng.integers(0, 200))
        a_lo, a_hi = _sorted_with_tail(rng, na, 320, pool)
        q_lo, q_hi = _sorted_with_tail(rng, nq, 220, pool)
        got = np.asarray(
            member_sorted(
                jnp.asarray(a_lo), jnp.asarray(a_hi),
                jnp.asarray(q_lo), jnp.asarray(q_hi),
                impl=impl, block=block,
            )
        )
        want = np.isin(_u64(q_lo, q_hi), _u64(a_lo, a_hi))
        assert (got == want).all(), trial


@pytest.mark.parametrize("impl", IMPLS)
def test_all_sentinel_and_empty_edges(impl):
    import jax.numpy as jnp

    from stateright_tpu.ops.merge import member_sorted, merge_sorted

    s = jnp.full(8, SENT, jnp.uint32)
    e = jnp.zeros(0, jnp.uint32)
    # all-sentinel × all-sentinel
    m_lo, m_hi = merge_sorted(s, s, s, s, impl=impl, block=16)
    assert (np.asarray(m_lo) == SENT).all()
    assert (np.asarray(m_hi) == SENT).all()
    assert np.asarray(
        member_sorted(s, s, s, s, impl=impl, block=16)
    ).all()
    # empty runs on either side
    m_lo, m_hi = merge_sorted(e, e, s, s, impl=impl)
    assert np.asarray(m_lo).shape == (8,)
    m_lo, m_hi = merge_sorted(s, s, e, e, impl=impl)
    assert np.asarray(m_lo).shape == (8,)
    assert np.asarray(member_sorted(e, e, s, s, impl=impl)).shape == (
        8,
    )
    assert not np.asarray(member_sorted(e, e, s, s, impl=impl)).any()
    assert np.asarray(member_sorted(s, s, e, e, impl=impl)).shape == (
        0,
    )


@pytest.mark.parametrize("impl", IMPLS)
def test_dedup_parity_vs_rebuild_sort_oracle(impl):
    """The full wave-dedup pipeline (candidate sort → adjacent-equal →
    membership → winner compaction → visited merge) picks exactly the
    winners the retired (V+B)-row stable rebuild sort picked — same
    winner SET and, per duplicated key, the same winning candidate
    position — and produces the same next visited prefix."""
    import jax.numpy as jnp
    from jax import lax

    from stateright_tpu.ops.merge import member_sorted, merge_sorted

    rng = np.random.default_rng(23)
    V_TOT, B = 140, 90
    for trial in range(6):
        pool = _tie_pool(rng, 40)
        # visited: sorted DISTINCT reals (the engine invariant),
        # sentinel tail to the fixed V_TOT shape
        vis = np.unique(
            rng.choice(pool, size=int(rng.integers(1, 120)),
                       replace=True)
        )
        v_lo, v_hi = _keys(
            np.concatenate(
                [vis,
                 np.full(V_TOT - len(vis),
                         np.uint64(0xFFFFFFFFFFFFFFFF))]
            )
        )
        # candidates: arbitrary order, dups, sentinel padding rows
        cand = rng.choice(pool, size=B, replace=True)
        cand[rng.random(B) < 0.2] = np.uint64(0xFFFFFFFFFFFFFFFF)
        c_lo, c_hi = _keys(cand)

        # -- the retired oracle: stable sort of (visited ++ cands) ----
        m = np.concatenate([_u64(v_lo, v_hi), cand])
        pos = np.concatenate(
            [np.zeros(V_TOT, np.int64), np.arange(1, B + 1)]
        )
        order = np.argsort(m, kind="stable")
        ms, ps = m[order], pos[order]
        real = ms != np.uint64(0xFFFFFFFFFFFFFFFF)
        prev_same = np.concatenate([[False], ms[1:] == ms[:-1]])
        o_new = real & ~prev_same & (ps > 0)
        oracle_pos = set(ps[o_new].tolist())
        oracle_vis = np.sort(np.concatenate([vis, ms[o_new]]))

        # -- the round-10 path ----------------------------------------
        ck_lo, ck_hi = jnp.asarray(c_lo), jnp.asarray(c_hi)
        cpos = jnp.arange(1, B + 1, dtype=jnp.uint32)
        s_hi, s_lo, s_pos = lax.sort((ck_hi, ck_lo, cpos), num_keys=2)
        realc = ~(
            (s_hi == jnp.uint32(SENT)) & (s_lo == jnp.uint32(SENT))
        )
        psame = jnp.concatenate(
            [
                jnp.zeros(1, bool),
                (s_hi[1:] == s_hi[:-1]) & (s_lo[1:] == s_lo[:-1]),
            ]
        )
        member = member_sorted(
            jnp.asarray(v_lo), jnp.asarray(v_hi), s_lo, s_hi,
            impl=impl, block=64,
        )
        is_new = realc & ~psame & ~member
        got_pos = set(np.asarray(s_pos)[np.asarray(is_new)].tolist())
        assert got_pos == oracle_pos, trial

        w_lo = jnp.where(is_new, s_lo, jnp.uint32(SENT))
        w_hi = jnp.where(is_new, s_hi, jnp.uint32(SENT))
        # winners are already in key order within the sorted array;
        # compact them the way the engine does (order-preserving)
        okey = jnp.where(
            is_new, jnp.arange(B, dtype=jnp.uint32), jnp.uint32(SENT)
        )
        _, w_lo, w_hi = lax.sort((okey, w_lo, w_hi), num_keys=1)
        m_lo, m_hi = merge_sorted(
            jnp.asarray(v_lo), jnp.asarray(v_hi), w_lo, w_hi,
            impl=impl, block=64,
        )
        got_vis = _u64(np.asarray(m_lo), np.asarray(m_hi))
        n_real = len(oracle_vis)
        assert (got_vis[:n_real] == oracle_vis).all(), trial
        assert (got_vis[n_real:] == np.uint64(0xFFFFFFFFFFFFFFFF)).all()


@pytest.mark.parametrize("impl", IMPLS)
def test_compact_winners_property(impl):
    """The order-preserving winner compaction (ops/merge.py,
    impl-adaptive: O(B) rank scatter on ``xla``, 4-lane sort on the
    pallas paths): both implementations agree with a numpy oracle —
    winners keep their key order, all three lanes sentinel past the
    winner count, and counts past ``nf`` truncate to the FIRST nf
    winners (the engine flags f_overflow separately)."""
    import jax.numpy as jnp

    from stateright_tpu.ops.merge import compact_winners

    rng = np.random.default_rng(7)
    B = 96
    for trial, (nf, p) in enumerate(
        [(96, 0.3), (40, 0.7), (7, 1.0), (5, 0.0), (1, 0.5)]
    ):
        is_new = rng.random(B) < p
        pos = rng.integers(1, B + 1, size=B).astype(np.uint32)
        lo = rng.integers(0, 2 ** 32, size=B, dtype=np.uint32)
        hi = rng.integers(0, 2 ** 32, size=B, dtype=np.uint32)
        nf_pos, w_lo, w_hi = compact_winners(
            jnp.asarray(is_new), jnp.asarray(pos), jnp.asarray(lo),
            jnp.asarray(hi), nf, impl=impl,
        )
        idx = np.nonzero(is_new)[0][:nf]
        exp = np.full((3, nf), SENT, np.uint32)
        exp[0, :len(idx)] = pos[idx]
        exp[1, :len(idx)] = lo[idx]
        exp[2, :len(idx)] = hi[idx]
        assert (np.asarray(nf_pos) == exp[0]).all(), (trial, impl)
        assert (np.asarray(w_lo) == exp[1]).all(), (trial, impl)
        assert (np.asarray(w_hi) == exp[2]).all(), (trial, impl)


@pytest.mark.parametrize("impl", IMPLS)
def test_engine_counts_and_paths_per_impl(impl):
    """End-to-end engine gate per merge implementation: 2pc rm=3
    count parity with the host oracle, discovery parity, and a
    replayable counterexample path (the plog child-lane rework)."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    # single-class ladders: the multi-class switch structure is
    # pinned by test_no_visited_scale_sort_in_wave_body and the lint
    # fixture; here only count/path parity per impl is under test, so
    # compile one wave variant, not 16.
    c = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=1 << 11,
        frontier_capacity=1 << 9,
        cand_capacity=1 << 11,
        track_paths=True,
        waves_per_sync=4,
        merge_impl=impl,
    )
    c.join()
    assert c.unique_state_count() == 288
    c.assert_properties()
    # the parent log must still reconstruct real paths
    disc = c.discovered_property_names()
    assert disc
    for name in disc:
        path = c.discovery(name)
        if path is not None:
            assert len(path.states()) >= 1


def test_sharded_engine_counts_per_impl():
    """The sharded engine's post-shuffle merge on the same streaming
    path: count parity across shard counts under the XLA fallback
    (the CPU-mesh invocation; the kernel itself is interpret-gated
    above and in the single-chip engine test)."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    for shards in (1, 2):
        c = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sharded_sortmerge(
            n_shards=shards,
            capacity=1 << 10,
            frontier_capacity=1 << 8,
            cand_capacity=1 << 10,
            track_paths=True,
            merge_impl="xla",
        )
        c.join()
        assert c.unique_state_count() == 288, shards
        c.assert_properties()


def test_no_visited_scale_sort_in_wave_body():
    """THE acceptance audit: the steady-state wave body contains no
    ``sort`` whose rows scale with the visited capacity C — every
    remaining sort is candidate-scale (the B-row order/compaction
    sorts and the tiled compaction's per-tile sorts). Before round 10
    the merge stage ran a ``(V_v + B)``-row 3-lane sort plus a
    ``(V_v + B)``-row winner-position sort per wave; at the fixture
    below the smallest such sort was v_min + B rows and the largest
    C + B."""
    from stateright_tpu.analysis.walker import iter_eqns
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    import jax
    import jax.numpy as jnp

    C, F, B = 1 << 13, 1 << 8, 1 << 9
    checker = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=C,
        frontier_capacity=F,
        cand_capacity=B,
        f_min=64,
        v_min=256,
        track_paths=True,
        waves_per_sync=4,
    )
    init = jnp.asarray(checker.encoded.init_vecs())
    seed_fn, _ = checker._build_programs(init.shape[0])
    carry_shapes = jax.eval_shape(seed_fn, init)
    closed = jax.make_jaxpr(checker._wave_body)(carry_shapes)
    sort_rows = [
        max(
            int(v.aval.shape[0])
            for v in site.eqn.invars
            if getattr(v.aval, "shape", None)
        )
        for site in iter_eqns(closed.jaxpr)
        if site.primitive == "sort"
    ]
    assert sort_rows, "wave body unexpectedly sort-free"
    # candidate-scale bound: every sort fits the candidate buffer
    # (+ the one-tile packed-append headroom); nothing reaches the
    # old v_min + B floor, let alone C.
    assert max(sort_rows) < 256 + B, sort_rows
    assert max(sort_rows) < C


def test_merge_impl_resolution_and_validation():
    import pytest as _pytest

    from stateright_tpu.ops.merge import default_impl, resolve_impl

    assert resolve_impl(None) == default_impl()
    assert resolve_impl("xla") == "xla"
    with _pytest.raises(ValueError):
        resolve_impl("nope")
