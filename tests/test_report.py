"""Direct tests for the Reporter path (report.rs:10-98 parity).

``checker.report()`` / ``WriteReporter.report_checking`` /
``report_discoveries`` are the CLI's entire output surface
(cli._report routes every check lane through them) but had no direct
coverage — a format drift would only have shown up as a human reading
CLI output. These tests pin:

* the reference text protocol (``Checking. states=…`` /
  ``Done. … sec=…`` / ``Discovered "name" classification path``),
* the fingerprint-only branch for ``track_paths=False`` engines,
* periodic ``report_checking`` callbacks from the host BFS loop,
* ``checker.report()`` emitting the final snapshot + discoveries,
* cli._report using the same Reporter (no private formatting).
"""

import io
import re

import pytest

from stateright_tpu.report import ReportData, Reporter, WriteReporter


def _increment_bfs():
    from stateright_tpu.models.increment import Increment

    return Increment(thread_count=2).checker().spawn_bfs()


def test_write_reporter_checking_formats():
    out = io.StringIO()
    r = WriteReporter(out)
    r.report_checking(ReportData(
        total_states=10, unique_states=7, max_depth=3,
        duration_sec=0.5, done=False,
    ))
    r.report_checking(ReportData(
        total_states=20, unique_states=14, max_depth=5,
        duration_sec=1.25, done=True,
    ))
    lines = out.getvalue().splitlines()
    assert lines[0] == "Checking. states=10, unique=7, depth=3"
    assert lines[1] == "Done. states=20, unique=14, depth=5, sec=1.250"


def test_report_discoveries_full_paths():
    c = _increment_bfs().join()
    assert "fin" in c.discoveries()
    out = io.StringIO()
    WriteReporter(out).report_discoveries(c)
    text = out.getvalue()
    # reference format: Discovered "name" classification <encoded path>
    m = re.search(
        r'^Discovered "fin" counterexample (\S+)$', text, re.M
    )
    assert m, text
    assert m.group(1) == c.discoveries()["fin"].encode()
    # the replayed steps follow, with action arrows between states
    assert "-- " in text and " -->" in text


def test_report_discoveries_fingerprint_only():
    from stateright_tpu.models.increment import Increment

    c = (
        Increment(thread_count=2)
        .checker()
        .spawn_tpu_sortmerge(
            capacity=1 << 12, frontier_capacity=256,
            cand_capacity=1024, track_paths=False,
        )
        .join()
    )
    fps = c.discovery_fingerprints()
    assert "fin" in fps
    out = io.StringIO()
    WriteReporter(out).report_discoveries(c)
    text = out.getvalue()
    assert re.search(
        r'^Discovered "fin" counterexample 0x[0-9a-f]{16} '
        r"\(fingerprint only", text, re.M
    ), text
    assert f"{fps['fin']:#018x}" in text


def test_checker_report_emits_final_snapshot_and_discoveries():
    c = _increment_bfs()
    out = io.StringIO()
    ret = c.report(WriteReporter(out))
    assert ret is c  # fluent, checker.rs:330-431
    text = out.getvalue()
    assert f"Done. states={c.state_count()}, " \
           f"unique={c.unique_state_count()}, " \
           f"depth={c.max_depth()}," in text
    assert 'Discovered "fin" counterexample' in text
    # join_and_report is an alias of the same path
    out2 = io.StringIO()
    c.join_and_report(WriteReporter(out2))
    assert "Done." in out2.getvalue()


def test_bfs_periodic_report_checking_callbacks():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    class Rec(Reporter):
        def __init__(self):
            self.snapshots = []

        def delay(self):
            return 0.0  # report after every popped state

        def report_checking(self, data):
            self.snapshots.append(data)

    rec = Rec()
    c = TwoPhaseSys(rm_count=3).checker().spawn_bfs()
    c.report(rec)
    # periodic (done=False) snapshots from inside the loop, then the
    # final done=True snapshot from report()
    assert len(rec.snapshots) >= 2
    assert any(not d.done for d in rec.snapshots[:-1])
    final = rec.snapshots[-1]
    assert final.done and final.unique_states == 288
    # progress is monotonic
    uniques = [d.unique_states for d in rec.snapshots]
    assert uniques == sorted(uniques)


def test_default_reporter_is_inert():
    r = Reporter()
    assert r.delay() == 1.0
    r.report_checking(ReportData(1, 1, 1, 0.0, True))  # no-op
    r.report_discoveries(_increment_bfs().join())  # no-op


def test_cli_report_routes_through_write_reporter():
    from stateright_tpu.cli import _report

    out = io.StringIO()
    _report(_increment_bfs(), out=out)
    text = out.getvalue()
    assert text.startswith("Done. states=")
    assert 'Discovered "fin" counterexample' in text
