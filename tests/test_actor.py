"""Actor framework: networks, ActorModel semantics, pinned state counts.

Ground-truth counts come from the reference's own tests (BASELINE.md):
ping-pong lossy-dup max1 = 14, lossy-dup max5 = 4,094, lossless
non-dup max5 = 11 (actor/model.rs:688, 847, 887); the no-op/network
interaction test (actor/model.rs no_op_depends_on_network) pins 2/2/3.
"""

import pytest

from stateright_tpu import Expectation
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Cow,
    Deliver,
    Drop,
    Envelope,
    Id,
    Network,
    Out,
)
from stateright_tpu.models.ping_pong import PingPongCfg, Ping, ping_pong_model


def test_ping_pong_lossy_dup_max1_visits_14_states():
    model = ping_pong_model(PingPongCfg(max_nat=1)).set_lossy_network(True)
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 14


def test_ping_pong_lossy_dup_max5_visits_4094_states():
    model = ping_pong_model(PingPongCfg(max_nat=5)).set_lossy_network(True)
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")
    # Can lose the first message and get stuck (actor/model.rs:847+).
    path = checker.assert_any_discovery("must reach max")
    assert path.actions() == [Drop(Envelope(Id(0), Id(1), Ping(0)))]


def test_ping_pong_lossless_nondup_max5_visits_11_states():
    model = ping_pong_model(PingPongCfg(max_nat=5)).init_network(
        Network.new_unordered_nonduplicating()
    )
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_ping_pong_history_properties():
    model = ping_pong_model(
        PingPongCfg(max_nat=3, maintains_history=True)
    ).init_network(Network.new_unordered_nonduplicating())
    checker = model.checker().spawn_bfs().join()
    checker.assert_no_discovery("#in <= #out")


def test_no_op_depends_on_network():
    # actor/model.rs no_op_depends_on_network: ignored messages are
    # pruned on unordered networks but must drain ordered channels.
    class Ignored:
        pass

    class MyActor(Actor):
        def __init__(self, server: Id | None):
            self.server = server

        def on_start(self, id, out):
            if self.server is not None:
                out.send(self.server, "ignored")
                out.send(self.server, "interesting")
            return "awaiting"

        def on_msg(self, id, state, src, msg, out):
            if msg == "interesting":
                state.set("got it")

    def build(network):
        return (
            ActorModel()
            .actor(MyActor(server=Id(1)))
            .actor(MyActor(server=None))
            .init_network(network)
            .property(Expectation.ALWAYS, "check everything", lambda m, s: True)
        )

    assert (
        build(Network.new_unordered_duplicating())
        .checker().spawn_bfs().join().unique_state_count()
    ) == 2
    assert (
        build(Network.new_unordered_nonduplicating())
        .checker().spawn_bfs().join().unique_state_count()
    ) == 2
    assert (
        build(Network.new_ordered())
        .checker().spawn_bfs().join().unique_state_count()
    ) == 3


def test_crash_fault_injection():
    # With one allowed crash, the receiver can die before delivery:
    # the ping is then undeliverable and counts stay at (0, 0).
    model = ping_pong_model(PingPongCfg(max_nat=2)).init_network(
        Network.new_unordered_nonduplicating()
    ).set_max_crashes(1)
    checker = model.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("must reach max")
    assert any("Crash" in type(a).__name__ for a in path.actions())


def test_timers_fire_and_clear():
    class TimerActor(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", (0.0, 0.0))
            return 0

        def on_timeout(self, id, state, timer, out):
            if state.value < 2:
                state.set(state.value + 1)
                out.set_timer("tick", (0.0, 0.0))

    model = (
        ActorModel()
        .actor(TimerActor())
        .property(
            Expectation.SOMETIMES, "reaches 2", lambda m, s: s.actor_states[0] == 2
        )
        .property(
            # The final timeout is a pure timer-removal (NOT a no-op:
            # is_no_op_with_timer only prunes same-timer renewals).
            Expectation.EVENTUALLY,
            "timer drained",
            lambda m, s: s.actor_states[0] == 2 and not s.timers_set[0],
        )
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()
    # (0,T) -> (1,T) -> (2,T) -> (2,∅)
    assert checker.unique_state_count() == 4


def test_ordered_network_fifo():
    # Sender emits A then B over an ordered network; receiver must see
    # A before B in every interleaving.
    class Sender(Actor):
        def on_start(self, id, out):
            out.send(Id(1), "A")
            out.send(Id(1), "B")
            return ()

    class Receiver(Actor):
        def on_start(self, id, out):
            return ()

        def on_msg(self, id, state, src, msg, out):
            state.set(state.value + (msg,))

    model = (
        ActorModel()
        .actor(Sender())
        .actor(Receiver())
        .init_network(Network.new_ordered())
        .property(
            Expectation.ALWAYS,
            "fifo",
            lambda m, s: s.actor_states[1] in ((), ("A",), ("A", "B")),
        )
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_no_discovery("fifo")
    assert checker.unique_state_count() == 3


def test_envelope_iteration_deterministic():
    n = Network.new_unordered_nonduplicating()
    for env in [
        Envelope(Id(0), Id(1), "x"),
        Envelope(Id(1), Id(0), "y"),
        Envelope(Id(0), Id(1), "x"),
    ]:
        n = n.send(env)
    assert len(n) == 3
    assert list(n.iter_deliverable()) == list(n.iter_deliverable())
    assert len(list(n.iter_all())) == 3
    n2 = n.on_deliver(Envelope(Id(0), Id(1), "x"))
    assert len(n2) == 2
    with pytest.raises(KeyError):
        n2.on_deliver(Envelope(Id(5), Id(6), "zzz"))


def test_network_from_name_roundtrip():
    for name in Network.names():
        assert Network.from_name(name) is not None
    with pytest.raises(ValueError):
        Network.from_name("carrier pigeon")
