"""Example workloads with reference-pinned unique-state counts.

All counts are implementation-independent ground truth from the
reference's own tests (BASELINE.md): 2pc 3 RMs = 288, 5 RMs = 8,832
(665 with symmetry); paxos 2c/3s = 16,668 (BFS and DFS agree);
ABD 2c/2s = 544; increment 2 threads = 13 (8 with symmetry).
"""

import pytest

from stateright_tpu.models.increment import Increment, IncrementLock
from stateright_tpu.models.linearizable_register import AbdModelCfg, abd_model
from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_2pc_3rms_288_states():
    checker = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_2pc_5rms_8832_states():
    checker = TwoPhaseSys(rm_count=5).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_2pc_5rms_symmetry_665_states():
    checker = (
        TwoPhaseSys(rm_count=5).checker().symmetry().spawn_dfs().join()
    )
    assert checker.unique_state_count() == 665
    checker.assert_properties()


def test_increment_race_found():
    checker = Increment(thread_count=2).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 13
    # The lost update is discovered.
    path = checker.assert_any_discovery("fin")
    final = path.last_state()
    assert final.i < sum(1 for p in final.s if p.pc >= 3)


def test_increment_symmetry_reduces_and_still_finds_race():
    # The doc-stated 8 equivalence classes (increment.rs module docs)
    # bound the reduced space; the checker early-exits once the "fin"
    # violation is discovered, so the visited count is <= 8 and < 13.
    checker = (
        Increment(thread_count=2).checker().symmetry().spawn_dfs().join()
    )
    assert checker.unique_state_count() <= 8
    checker.assert_any_discovery("fin")


def test_increment_lock_holds():
    checker = IncrementLock(thread_count=2).checker().spawn_bfs().join()
    checker.assert_properties()  # both "fin" and "mutex" hold


def test_increment_lock_symmetry_agrees():
    plain = IncrementLock(thread_count=3).checker().spawn_dfs().join()
    sym = IncrementLock(thread_count=3).checker().symmetry().spawn_dfs().join()
    assert sym.unique_state_count() < plain.unique_state_count()
    sym.assert_properties()


def test_abd_2c2s_544_states():
    checker = abd_model(AbdModelCfg(client_count=2, server_count=2)).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 544


@pytest.mark.slow
def test_paxos_2c3s_16668_states_bfs():
    checker = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 16668


@pytest.mark.slow
def test_paxos_2c3s_16668_states_dfs():
    checker = (
        paxos_model(PaxosModelCfg(client_count=2, server_count=3))
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 16668
