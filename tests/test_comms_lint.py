"""The comms-lint gate (``pytest -m lint``, round 13).

Same two-halves structure as the codegen gate (tests/test_lint.py):

* the GATE — the comms rule family over both sharded engines' wave
  bodies (traced + untraced, real S=2 mesh), the rm=5/S=8
  reconciliation fixture, and every registry encoding's sharded pair
  pipeline comes back clean (what ``tools/lint_comms.py`` exits 0 on);
* the TEETH — deliberate regressions (a collective moved inside a
  shard-varying switch, a psum over a resident-shaped buffer, an
  all_to_all fed by unsorted candidates, an injected all_gather, an
  over-budget shuffle) each caught by the NAMED rule with source
  attribution;
* the RECONCILIATION — the static per-row byte price from the traced
  all_to_all equals the committed TRACE_r16 mesh trace's
  ``dest_tile_lanes``-derived price, so measured routed bytes ARE
  routed_rows x the static row_bytes, exactly (the estimate-vs-
  measured bound PERF.md §comms-lint states).
"""

import json
import os

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from stateright_tpu.analysis import (  # noqa: E402
    COMMS_RULES,
    ENCODINGS,
    TraceCtx,
    reconcile_collective_categories,
    run_comms_lint,
    run_rules,
)
from stateright_tpu.analysis.comms import (  # noqa: E402
    RECONCILIATION_CONFIG,
    RECONCILIATION_FIXTURE,
    comms_fixture_name,
)
from stateright_tpu.analysis.tables import (  # noqa: E402
    COMMS_BYTE_BUDGETS,
    SCALAR_REDUCTION_MAX_ELEMS,
)

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map
    kw = {} if hasattr(lax, "pvary") else {"check_rep": False}
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def _mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("shard",))


def _ctx(name="synthetic", seam=None):
    return TraceCtx(
        path="wave-body", encoding=name, n=64, k=0, sparse=False,
        allow_gathers=None, check_lane_alu=False,
        check_branches=False, check_comms=True, routing_seam=seam,
    )


# -- the gate --------------------------------------------------------------


@pytest.fixture(scope="module")
def gate_report():
    """ONE full run_comms_lint() serves every gate assertion: each
    run rebuilds both sharded engines x traced/untraced (incl. the
    S=8 rm=5 reconciliation engine) and harvests every registry
    encoding, and the output is deterministic (pinned by the verify
    skill's json-compare probe) — re-running per test only re-buys
    the build cost."""
    return run_comms_lint()


def test_comms_lint_clean_all_fixtures(gate_report):
    """Both sharded engines x traced/untraced bodies, the
    reconciliation fixture, and every registry encoding's sharded
    pipeline: zero gated comms findings. This is the tier-1 mesh
    communication-contract gate."""
    report = gate_report
    errors = [
        f for f in report["findings"] if f["severity"] == "error"
    ]
    assert report["clean"], errors
    covered = {(p["encoding"], p["path"]) for p in report["paths"]}
    for engine in ("sortmerge", "hash"):
        for traced in (False, True):
            name = comms_fixture_name(engine, traced)
            assert (name, "wave-body") in covered, name
    # the TIERED chunk program (round 16, stateright_tpu/tier.py)
    # rides the same gate: its deferred-commit phase must stay
    # collective-clean too
    assert (
        comms_fixture_name("sortmerge", True, tiered=True),
        "wave-body",
    ) in covered
    assert (RECONCILIATION_FIXTURE, "wave-body") in covered
    for spec in ENCODINGS:
        assert (spec.name, "engine:sharded") in covered, spec.name
    # every wave-body fixture's accounting made it into the comms
    # block with the reconciliation fields present where a shuffle is
    for name, c in report["comms"].items():
        assert c["collectives"] > 0, name
        assert c["all_to_all_row_bytes"] == 4 * c["dest_tile_lanes"]


def test_comms_registry_names_all_rules():
    assert {r.name for r in COMMS_RULES} == {
        "no-collective-in-switch", "no-unsorted-all-to-all",
        "scalar-only-reductions", "no-all-gather", "comms-bytes",
    }


def test_comms_budgets_have_teeth(gate_report):
    """Every wave-body fixture is budgeted, under budget, and the
    budget is not slack past 2x the measured per-wave peak (the same
    has-teeth policy as the carry-copy budgets)."""
    for name, c in gate_report["comms"].items():
        budget = COMMS_BYTE_BUDGETS[name]
        assert c["budget_bytes"] == budget, name
        assert c["per_wave_peak_bytes"] <= budget, name
        assert budget < 2 * c["per_wave_peak_bytes"], name


def test_traced_mesh_log_adds_no_collective_traffic(gate_report):
    """The per-shard mesh log's contract is 'never psum-collapsed':
    the traced wave body's per-wave collective peak may exceed the
    untraced one by at most ONE scalar psum (the global wave row's
    n_tot back-fill, 4 bytes) — the telemetry layer rides the
    existing sync, it does not add traffic."""
    for engine in ("sortmerge", "hash"):
        plain = gate_report["comms"][
            comms_fixture_name(engine, False)
        ]
        traced = gate_report["comms"][
            comms_fixture_name(engine, True)
        ]
        delta = (
            traced["per_wave_peak_bytes"]
            - plain["per_wave_peak_bytes"]
        )
        assert 0 <= delta <= 4, (engine, delta)


# -- the teeth -------------------------------------------------------------


def test_comms_catches_collective_in_varying_switch():
    """A collective under a switch whose index is derived from
    SHARD-LOCAL data (not pmax-agreed) is the deadlock hazard the
    documented invariant forbids — caught with both the collective's
    and the switch's source attribution. The same body with a
    pmax-agreed index passes."""
    mesh = _mesh2()

    def br(v):
        return (
            lax.psum(jnp.sum(v) * 0, "shard") + jnp.sum(v)
        ).reshape(1)

    def bad(x):
        # index from the shard-LOCAL row count: shards can disagree
        idx = (jnp.sum(x) % 2).astype(jnp.int32)
        return lax.switch(idx, [br, br], x)

    def good(x):
        agreed = lax.pmax(jnp.sum(x) % 2, "shard").astype(jnp.int32)
        return lax.switch(agreed, [br, br], x)

    arg = jnp.zeros((2, 8), jnp.uint32)
    jx_bad = jax.make_jaxpr(
        _shard_map(bad, mesh, (P("shard"),), P("shard"))
    )(arg)
    hits = [
        f for f in _errors(run_rules(_ctx(), jx_bad))
        if f.rule == "no-collective-in-switch"
    ]
    assert hits, "shard-varying switch index not caught"
    assert hits[0].primitive == "psum"
    assert hits[0].source
    assert hits[0].data["switch_source"]
    jx_good = jax.make_jaxpr(
        _shard_map(good, mesh, (P("shard"),), P("shard"))
    )(arg)
    assert not [
        f for f in _errors(run_rules(_ctx(), jx_good))
        if f.rule == "no-collective-in-switch"
    ], "pmax-agreed switch index must pass"


def test_comms_catches_varying_switch_via_loop_carry():
    """Taint that only develops through a while-loop round trip still
    reaches a carried switch index (review finding: without the
    loop-carry feedback edge in walker._flow, a carry that starts
    uniform but is overwritten with axis_index-derived data inside
    the body read as uniform forever — and the rule passed the
    deadlock shape clean)."""
    mesh = _mesh2()

    def br(v):
        return (
            lax.psum(jnp.sum(v) * 0, "shard") + jnp.sum(v)
        ).reshape(1)

    def looped(x):
        def body(carry):
            i, idx, acc = carry
            picked = lax.switch(idx, [br, br], x)
            # from iteration 2 on, the carried index is shard-LOCAL
            next_idx = (
                lax.axis_index("shard") % 2
            ).astype(jnp.int32)
            return (i + 1, next_idx, acc + picked)

        _, _, out = lax.while_loop(
            lambda c: c[0] < 3,
            body,
            (jnp.int32(0), jnp.int32(0), jnp.zeros(1, jnp.uint32)),
        )
        return out

    jx = jax.make_jaxpr(
        _shard_map(looped, mesh, (P("shard"),), P("shard"))
    )(jnp.zeros((2, 8), jnp.uint32))
    hits = [
        f for f in _errors(run_rules(_ctx(), jx))
        if f.rule == "no-collective-in-switch"
    ]
    assert hits, "loop-carried shard-varying switch index not caught"


def test_comms_catches_buffer_sized_reduction():
    """A psum over a resident-shaped [W, F] buffer is accidental
    replication — caught with the operand shape in the finding; the
    engines' scalar psums pass."""
    mesh = _mesh2()
    W, F = 20, 512
    assert W * F > SCALAR_REDUCTION_MAX_ELEMS

    def bad(x):
        return lax.psum(x, "shard")

    jx = jax.make_jaxpr(
        _shard_map(bad, mesh, (P(None, "shard"),), P())
    )(jnp.zeros((W, 2 * F), jnp.uint32))
    hits = [
        f for f in _errors(run_rules(_ctx(), jx))
        if f.rule == "scalar-only-reductions"
    ]
    assert hits, "buffer-sized psum not caught"
    assert hits[0].data["elements"] == W * F
    assert str(F) in hits[0].message
    assert hits[0].source

    def good(x):
        return lax.psum(jnp.sum(x), "shard")

    jx2 = jax.make_jaxpr(
        _shard_map(good, mesh, (P(None, "shard"),), P())
    )(jnp.zeros((W, 2 * F), jnp.uint32))
    assert not [
        f for f in _errors(run_rules(_ctx(), jx2))
        if f.rule == "scalar-only-reductions"
    ]


def test_comms_catches_unsorted_all_to_all():
    """An all_to_all fed raw candidates (no routing sort upstream)
    breaks the owner-local dedup contract — caught under the "sort"
    seam; the sorted variant passes, including when the sort sits in
    an enclosing scope and flows in through a switch branch."""
    mesh = _mesh2()
    rows = jnp.zeros((8, 4), jnp.uint32)

    def bad(x):
        return lax.all_to_all(
            x, "shard", split_axis=0, concat_axis=0, tiled=True
        )

    def good(x):
        owner = x[:, 0] % 2
        _, s_row = lax.sort(
            (owner, jnp.arange(x.shape[0], dtype=jnp.uint32)),
            num_keys=2,
        )
        routed = x[s_row]
        return lax.all_to_all(
            routed, "shard", split_axis=0, concat_axis=0, tiled=True
        )

    for fn, should_hit in ((bad, True), (good, False)):
        jx = jax.make_jaxpr(
            _shard_map(fn, mesh, (P("shard"),), P("shard"))
        )(rows)
        hits = [
            f for f in _errors(run_rules(_ctx(seam="sort"), jx))
            if f.rule == "no-unsorted-all-to-all"
        ]
        assert bool(hits) == should_hit, (fn.__name__, hits)
        if hits:
            assert hits[0].source


def test_comms_catches_injected_all_gather():
    """An all_gather on a wave path is the S-fold traffic blow-up —
    caught at the default zero allowance; a registered drain-path
    allowance (tables.ALL_GATHER_ALLOWANCES) lets the same trace
    pass."""
    from stateright_tpu.analysis.tables import ALL_GATHER_ALLOWANCES

    mesh = _mesh2()

    def gathers(x):
        return lax.all_gather(x, "shard")

    jx = jax.make_jaxpr(
        _shard_map(gathers, mesh, (P("shard"),), P())
    )(jnp.zeros((8, 4), jnp.uint32))
    hits = [
        f for f in _errors(run_rules(_ctx(), jx))
        if f.rule == "no-all-gather"
    ]
    assert hits, "injected all_gather not caught"
    assert hits[0].data["all_gathers"] >= 1
    assert hits[0].source
    name = "synthetic-drain"
    ALL_GATHER_ALLOWANCES[name] = hits[0].data["all_gathers"]
    try:
        assert not [
            f for f in _errors(run_rules(_ctx(name=name), jx))
            if f.rule == "no-all-gather"
        ], "drain-path allowance not honored"
    finally:
        del ALL_GATHER_ALLOWANCES[name]


def test_comms_catches_byte_budget_regression():
    """A wave body whose per-wave collective payload exceeds its
    fixture budget fails the gated comms-bytes rule naming both
    numbers (the silent-8x-traffic failure mode, now loud)."""
    mesh = _mesh2()
    name = "synthetic-budgeted"
    COMMS_BYTE_BUDGETS[name] = 1024

    def fat(x):
        owner = x[:, 0] % 2
        _, s_row = lax.sort(
            (owner, jnp.arange(x.shape[0], dtype=jnp.uint32)),
            num_keys=2,
        )
        return lax.all_to_all(
            x[s_row], "shard", split_axis=0, concat_axis=0,
            tiled=True,
        )

    try:
        jx = jax.make_jaxpr(
            _shard_map(fat, mesh, (P("shard"),), P("shard"))
        )(jnp.zeros((512, 8), jnp.uint32))
        hits = [
            f for f in _errors(
                run_rules(_ctx(name=name, seam="sort"), jx)
            )
            if f.rule == "comms-bytes"
        ]
        assert hits, "over-budget shuffle not gated"
        assert hits[0].data["per_wave_peak_bytes"] > 1024
        assert "1,024" in hits[0].message
    finally:
        del COMMS_BYTE_BUDGETS[name]


def test_comms_peak_maxes_nested_switch_siblings():
    """Per-wave peak accounting at NESTED switches (review finding):
    two collectives in mutually exclusive branches of an inner cond
    must contribute max(), not sum() — only one runs per wave — while
    collectives under distinct sequential conds still sum."""
    mesh = _mesh2()

    def br_coll(rows):
        def br(v):
            return (
                lax.psum(jnp.sum(v) * 0, "shard") + jnp.sum(v)
            ).reshape(1)

        return br

    def nested(x):
        agreed = lax.pmax(jnp.sum(x) % 2, "shard").astype(jnp.int32)

        def outer0(v):
            def inner(w):
                # two sibling branches, one 512-row all_to_all each
                def ib(u):
                    owner = u[:, 0] % 2
                    _, s_row = lax.sort(
                        (owner,
                         jnp.arange(u.shape[0], dtype=jnp.uint32)),
                        num_keys=2,
                    )
                    return lax.all_to_all(
                        u[s_row], "shard", split_axis=0,
                        concat_axis=0, tiled=True,
                    )

                return lax.switch(
                    lax.pmax(
                        jnp.sum(w) % 2, "shard"
                    ).astype(jnp.int32),
                    [ib, ib],
                    w,
                )

            return inner(v)

        def outer1(v):
            return v

        return lax.switch(agreed, [outer0, outer1], x)

    rows = jnp.zeros((512, 8), jnp.uint32)
    jx = jax.make_jaxpr(
        _shard_map(nested, mesh, (P("shard"),), P("shard"))
    )(rows)
    findings = run_rules(_ctx(seam="sort"), jx)
    assert not _errors(findings)
    est = [f for f in findings if f.rule == "comms-bytes"][0]
    a2a_bytes = est.data["per_category"]["all-to-all"]["bytes"]
    # two sibling all_to_alls in the program total, ONE in the peak
    assert est.data["all_to_all_eqns"] == 2
    peak = est.data["per_wave_peak_bytes"]
    assert peak < a2a_bytes  # not the sum of both siblings
    assert peak >= a2a_bytes // 2  # but at least the fattest one


def test_hlo_reconcile_flags_introduced_collectives():
    """The --hlo cross-check's verdict logic: an HLO category with
    MORE ops than the jaxpr accounts for (SPMD respecification) is a
    gated finding; fewer is an info; equal counts with any byte ratio
    are clean."""
    jaxpr_side = {
        "all-to-all": {"eqns": 4, "bytes": 204288},
        "reduction": {"eqns": 55, "bytes": 348},
    }
    clean = reconcile_collective_categories(
        "fx", {
            "all-to-all": {"ops": 4, "bytes": 204288},
            "reduction": {"ops": 55, "bytes": 348},
        }, jaxpr_side,
    )
    assert not clean["findings"]
    assert clean["byte_ratio"]["all-to-all"] == 1.0
    introduced = reconcile_collective_categories(
        "fx", {
            "all-to-all": {"ops": 4, "bytes": 204288},
            "reduction": {"ops": 55, "bytes": 348},
            "all-gather": {"ops": 1, "bytes": 8192},
        }, jaxpr_side,
    )
    errs = _errors(introduced["findings"])
    assert errs and errs[0].rule == "hlo-collective-reconcile"
    assert errs[0].data == {"hlo_ops": 1, "jaxpr_eqns": 0}
    folded = reconcile_collective_categories(
        "fx", {
            "all-to-all": {"ops": 4, "bytes": 204288},
            "reduction": {"ops": 50, "bytes": 300},
        }, jaxpr_side,
    )
    assert not _errors(folded["findings"])
    assert any(
        f.severity == "info" for f in folded["findings"]
    )


# -- the reconciliation ----------------------------------------------------


def test_comms_static_reconciles_trace_r16(gate_report):
    """The static comms-bytes estimate vs the committed 2pc rm=5 mesh
    trace (TRACE_r16, the dryrun_multichip flagship run): the traced
    all_to_all's per-row byte price equals the runtime lane's
    dest_tile_lanes price EXACTLY, so the trace's routed-byte total
    IS routed_rows x the static row_bytes, and every wave's routed
    rows sit under the static per-wave row ceiling (S x dest_cap =
    the all_to_all's operand rows). The static side comes from the
    gate's own reconciliation fixture (same engine config the trace
    ran under) — no rebuild."""
    from stateright_tpu.telemetry import shard_balance

    with open(os.path.join(_REPO, "TRACE_r16.jsonl")) as fh:
        events = [json.loads(line) for line in fh]
    bal = shard_balance(events)
    assert bal is not None and bal["n_shards"] == 8

    summary = gate_report["comms"][RECONCILIATION_FIXTURE]
    assert summary["n_shards"] == RECONCILIATION_CONFIG["n_shards"]

    # static row price == runtime lane price, exactly
    row_bytes = summary["all_to_all_row_bytes"]
    cs = bal["comms_static"]
    assert row_bytes == cs["row_bytes"] == 28
    # measured routed bytes ARE routed rows x the static price
    assert bal["routed_rows_total"] == 32580
    assert (
        bal["routed_bytes_total"]
        == cs["measured_routed_bytes"]
        == bal["routed_rows_total"] * row_bytes
    )
    # the static per-wave ceiling holds wave for wave: S x dest_cap
    # rows is what the all_to_all exchanges, and the traced operand
    # agrees with it
    assert summary["all_to_all_rows_max"] == 8 * 1024
    for w in bal["per_wave"]:
        bound = w["shards"] * w["dest_cap"]
        assert w["routed_rows"] <= bound
        assert bound <= summary["all_to_all_rows_max"]
    assert cs["bytes_bound_total"] == (
        cs["bound_rows_total"] * row_bytes
    )
    assert 0 < cs["bound_util"] <= 1


# -- layout-separation pin (satellite: payload_pack claim) -----------------


def test_sharded_engine_never_calls_payload_pack():
    """payload_pack's docstring claims the single-chip payload layout
    and the sharded routed-tile layout never meet (dest_tile_pack is
    the sharded home). The comms walk found no reuse; this pins the
    claim at the AST level so a future call-site can't quietly merge
    the two layouts without updating both docstrings."""
    import ast

    path = os.path.join(
        _REPO, "stateright_tpu", "parallel", "engine_sortmerge.py"
    )
    with open(path) as fh:
        tree = ast.parse(fh.read())
    calls = {
        node.func.id
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
    }
    assert "payload_pack" not in calls
    assert "dest_tile_pack" in calls


# -- artifact cross-reference (COMM_r*.json) -------------------------------


def test_latest_comms_summary_reads_committed_artifact():
    """The committed COMM_r01.json parses into the cross-reference
    block bench.py / lint_kernels.py embed: artifact name, clean flag,
    and the per-fixture reconciliation numbers (the 28 B/row price
    TRACE_r16's routed counters multiply against)."""
    from stateright_tpu.artifacts import latest_comms_summary

    ref = latest_comms_summary()
    assert ref is not None
    assert ref["artifact"].startswith("COMM_r")
    assert ref["clean"] is True
    fx = ref["fixtures"][RECONCILIATION_FIXTURE]
    assert fx["all_to_all_row_bytes"] == 28
    assert fx["per_wave_peak_bytes"] > 0


def test_latest_comms_summary_best_effort(tmp_path):
    """Missing, truncated, or structurally mangled COMM artifacts
    degrade to None — same contract as latest_lint_summary (a
    hand-edited artifact must never abort bench.py at startup)."""
    from stateright_tpu.artifacts import latest_comms_summary

    root = str(tmp_path)
    assert latest_comms_summary(root) is None
    p = tmp_path / "COMM_r01.json"
    p.write_text("{ truncated")
    assert latest_comms_summary(root) is None
    p.write_text(json.dumps({"clean": True, "comms": "not-a-dict"}))
    assert latest_comms_summary(root) is None
    p.write_text(json.dumps({
        "clean": True,
        "comms": {"fx": {"per_wave_peak_bytes": 7,
                         "all_to_all_row_bytes": 28}},
        "provenance": {"git_sha": "f" * 40},
    }))
    ref = latest_comms_summary(root)
    assert ref == {
        "artifact": "COMM_r01.json",
        "clean": True,
        "git_sha": "f" * 40,
        # foreign SHA against this checkout's HEAD (and a dirty tree
        # during development): the pairing claim stays unknown/False,
        # never a crash
        "sha_matches_head": ref["sha_matches_head"],
        "fixtures": {"fx": {"per_wave_peak_bytes": 7,
                            "all_to_all_row_bytes": 28}},
    }
    assert ref["sha_matches_head"] in (None, False)


def test_comm_artifacts_number_in_own_sequence(tmp_path):
    """COMM rounds count independently of the shared
    BENCH/LINT/TRACE sequence (the MEM pattern): a repo at shared
    round 9 still writes COMM_r01 first."""
    from stateright_tpu import artifacts

    root = str(tmp_path)
    open(os.path.join(root, "TRACE_r08.jsonl"), "w").close()
    assert artifacts.next_round(root, stems=("COMM",)) == 1
    open(os.path.join(root, "COMM_r01.json"), "w").close()
    assert artifacts.next_round(root, stems=("COMM",)) == 2
    assert artifacts.next_round(root) == 9
