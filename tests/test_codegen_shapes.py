"""Codegen-shape tests: the compiled-actor op shapes that PERF.md
§ordered priced — pinned at the jaxpr level so the 8x
compiled-codegen tax can't silently regress on CPU-only CI.

The round-5 device trace attributed the compiled path's per-state cost
to two codegen artifacts:

* ~1.6s/run of 1-D gathers inside the generated enabled mask (per-slot
  table gathers where hand encodings use shift-mask field extracts) —
  so the MASK path must contain NO gather primitives at all, never
  materialize the dense ``[N, K]`` bool mask, and emit no ``[N, 1]``
  elementwise ALU ops;
* ~470ms/run of ``[N, 1]``-shaped elementwise ops (stack-of-scalars
  concats whose operands pay the full 128-lane tile-padding tax, and
  which XLA cannot fuse through a concatenate) — so the STEP path must
  emit no ``[N, 1]`` ALU ops and no wide concatenates of ``[N, 1]``
  lanes.

Calibration: the allowed residue matches what the HAND paxos encoding
(models/paxos_tpu.py, the 2M st/s reference point) emits under the
same audit — table-row gathers by traced slot (the intended sparse
idiom), ``[N, 1]`` slices from consuming multi-lane gather rows, and
2-operand ``[N, 1]`` concats that build gather index pairs. Those
fuse; ``[N, 1]`` COMPUTE and mask-path gathers do not.

Round 7: the walk and the primitive tables moved to
``stateright_tpu/analysis`` (walker.audit_jaxpr / tables.ALU_PRIMS) —
one copy shared with the kernel-lint rules (``pytest -m lint``,
tools/lint_kernels.py) and the wave-wall profiler's HLO attribution,
so the three audits cannot drift. These tests keep the calibrated
assertions; the lint runs the same tables as declarative rules over
every registered encoding.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.actor import Network  # noqa: E402
from stateright_tpu.actor.compile import compile_actor_model  # noqa: E402
from stateright_tpu.analysis import audit_jaxpr  # noqa: E402
from stateright_tpu.models.ping_pong import (  # noqa: E402
    PingPongCfg,
    ping_pong_device_specs as ping_pong_specs,
    ping_pong_model,
)
from stateright_tpu.ops.bitmask import (  # noqa: E402
    bit_select,
    mask_to_words,
    mask_words,
    pack_bits_host,
    popcount_words,
    words_to_mask,
)

pytestmark = pytest.mark.lint  # part of the kernel-lint tier-1 gate

N = 64  # batch rows in every traced vmap


def _audit_enc(enc):
    vecs = jnp.zeros((N, enc.width), jnp.uint32)
    slots = jnp.zeros((N,), jnp.uint32)
    out = {}
    for label, jx in (
        ("bits", jax.make_jaxpr(jax.vmap(enc.enabled_bits_vec))(vecs)),
        ("mask", jax.make_jaxpr(jax.vmap(enc.enabled_mask_vec))(vecs)),
        (
            "step",
            jax.make_jaxpr(jax.vmap(enc.step_slot_vec))(vecs, slots),
        ),
    ):
        out[label] = audit_jaxpr(jx, n=N, k=enc.max_actions)
    return out


def _assert_shapes(enc):
    a = _audit_enc(enc)
    # Mask path: pure shift-mask field extracts. No gathers anywhere
    # (the packed-words path and the derived dense view alike), no
    # [N, 1] ALU, and the packed path never materializes bool [N, K].
    assert a["bits"]["gathers"] == 0, "enabled_bits_vec has gathers"
    assert a["mask"]["gathers"] == 0, "enabled_mask_vec has gathers"
    assert a["bits"]["alu_n1"] == [], a["bits"]["alu_n1"]
    assert a["bits"]["bool_nk"] == [], (
        "enabled_bits_vec materializes the dense [N, K] bool mask"
    )
    assert a["bits"]["wide_concat_n1"] == 0
    # Step path: the four row-table gathers (params, flat transition,
    # packed history, crash mask) are the intended sparse idiom —
    # everything else is 1-D lane ALU. No [N, 1] compute, no
    # stack-of-scalars concats.
    assert a["step"]["gathers"] <= 4, (
        f"step_slot_vec grew table gathers: {a['step']['gathers']}"
    )
    assert a["step"]["alu_n1"] == [], a["step"]["alu_n1"]
    assert a["step"]["wide_concat_n1"] == 0, (
        "step_slot_vec stacks per-lane scalars through [N, 1] concats"
    )
    return a


def _ping_pong(network=None, **cfg_kw):
    cfg = PingPongCfg(**cfg_kw)
    model = ping_pong_model(cfg)
    if network is not None:
        model = model.init_network(network)
    return model, ping_pong_specs(cfg)


def test_codegen_shapes_unordered_nondup():
    model, specs = _ping_pong(
        Network.new_unordered_nonduplicating(), max_nat=3
    )
    enc = compile_actor_model(model, **specs)
    _assert_shapes(enc)


def test_codegen_shapes_unordered_dup_lossy():
    model, specs = _ping_pong(max_nat=2)
    enc = compile_actor_model(model.set_lossy_network(True), **specs)
    _assert_shapes(enc)


def test_codegen_shapes_ordered_integer_queues():
    """The FIFO lane (abd-ordered's shape family): integer-queue pop,
    head-match presence, and send-append chains must all trace to 1-D
    lane ops."""
    model, specs = _ping_pong(Network.new_ordered(), max_nat=3)
    enc = compile_actor_model(model, **specs, closure="reachable")
    _assert_shapes(enc)


def test_codegen_shapes_timers_and_crashes():
    from stateright_tpu.actor import Actor, ActorModel

    class Ticker(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", (1.0, 2.0))
            return 0

        def on_msg(self, id, state, src, msg, out):
            pass

        def on_timeout(self, id, state, timer, out):
            if state.value < 2:
                state.set(state.value + 1)
                out.set_timer("tick", (1.0, 2.0))

    model = (
        ActorModel(cfg=None).actor(Ticker()).actor(Ticker())
        .set_max_crashes(1)
    )
    enc = compile_actor_model(model, properties={})
    _assert_shapes(enc)


def test_bits_agree_with_dense_mask_and_validity():
    """The packed words ARE the mask: words_to_mask(enabled_bits_vec)
    equals enabled_mask_vec equals step_vec validity, over every
    reachable state of the nondup ping-pong."""
    from collections import deque

    model, specs = _ping_pong(
        Network.new_unordered_nonduplicating(), max_nat=3
    )
    enc = compile_actor_model(model, **specs)
    seen = set()
    q = deque(model.init_states())
    for s in list(q):
        seen.add(tuple(enc.encode(s).tolist()))
    while q:
        s = q.popleft()
        for n2 in model.next_states(s):
            if not model.within_boundary(n2):
                continue
            k = tuple(enc.encode(n2).tolist())
            if k not in seen:
                assert len(seen) < 5000
                seen.add(k)
                q.append(n2)
    vecs = jnp.asarray(np.array(sorted(seen), dtype=np.uint32))
    bits = np.asarray(jax.jit(jax.vmap(enc.enabled_bits_vec))(vecs))
    mask = np.asarray(jax.jit(jax.vmap(enc.enabled_mask_vec))(vecs))
    unpacked = np.asarray(
        words_to_mask(jnp, jnp.asarray(bits), enc.max_actions)
    )
    assert (unpacked == mask).all()
    _, valid, _ = jax.jit(jax.vmap(enc.step_vec))(vecs)
    assert (mask == np.asarray(valid)).all()
    counts = np.asarray(popcount_words(jnp, jnp.asarray(bits)))
    assert (counts == mask.sum(axis=1)).all()


def _audit_engine_pair_pipeline(enc):
    """jaxpr audit of the ENGINE's shared sparse pair pipeline
    (sparse_pair_candidates) at N frontier rows — the path both
    sort-merge engines run every wave. Calibrated like _audit above:
    the pair grid is [N, pair_width] by design, so the banned shape is
    the dense [N, K] bool mask (and any gather at all — the bitmap
    predicate, peel, and packed-append compaction are elementwise +
    sort only)."""
    from stateright_tpu.checkers.tpu_sortmerge import (
        sparse_pair_candidates,
    )

    from stateright_tpu.analysis.lint import engine_pair_width

    K = enc.max_actions
    EV = engine_pair_width(enc)  # the lint traces the same pipeline
    assert EV < K, "audit needs a real sparse pair width"

    def pipe(frontier_t, fval):
        return sparse_pair_candidates(
            enc, frontier_t, fval, jnp.bool_(True),
            EV=EV, B_p=N * EV, NT=1, T=N,
            mask_budget_cells=1 << 30, Ba=N * EV,
        )

    # The [W, N] resident layout (round 9, PERF.md §layout) — the
    # engines pass the transposed frontier block.
    jx = jax.make_jaxpr(pipe)(
        jnp.zeros((enc.width, N), jnp.uint32),
        jnp.zeros((N,), bool),
    )
    return audit_jaxpr(jx, n=N, k=K)


def test_engine_path_no_dense_mask_hand_paxos():
    """No dense [F, K] bool — and no gather — anywhere on the sparse
    engine path for the HAND paxos encoding (round 6: the engine's
    [F, K] predicate pass was the largest in-stage term at paxos-4
    shapes; the word-native enabled_bits_vec removes it)."""
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.paxos_tpu import PaxosEncoded

    enc = PaxosEncoded(PaxosModelCfg(client_count=2, server_count=3))
    s = _audit_engine_pair_pipeline(enc)
    assert s["bool_nk"] == [], (
        "dense [F, K] bool on the hand-paxos engine path"
    )
    assert s["gathers"] == 0, s["gathers"]


def test_engine_path_no_dense_mask_compiled_abd():
    """Same audit for a COMPILED encoding (ordered ABD, the
    abd-ordered bench lane's shape family)."""
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    model = abd_model(
        AbdModelCfg(client_count=2, server_count=2),
        Network.new_ordered(),
    )
    enc = model.to_encoded()
    s = _audit_engine_pair_pipeline(enc)
    assert s["bool_nk"] == [], (
        "dense [F, K] bool on the compiled-ABD engine path"
    )
    assert s["gathers"] == 0, s["gathers"]


def test_codegen_shapes_hand_encodings():
    """The hand encodings' word-native mask paths meet the same bar
    the compiled codegen is held to: no gathers, no [N, 1] ALU, no
    dense [N, K] bool from the packed path. (Their step paths keep
    the intended table-row-gather idiom: 2pc needs zero — its slot
    constants are arithmetic in the slot index — and paxos its two
    packed table rows.)"""
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.paxos_tpu import PaxosEncoded
    from stateright_tpu.models.two_phase_commit_tpu import (
        TwoPhaseSysEncoded,
    )

    for enc, max_step_gathers in (
        (PaxosEncoded(PaxosModelCfg(client_count=2, server_count=3)),
         4),
        (TwoPhaseSysEncoded(4), 0),
    ):
        a = _audit_enc(enc)
        assert a["bits"]["gathers"] == 0, type(enc).__name__
        assert a["mask"]["gathers"] == 0, type(enc).__name__
        assert a["bits"]["alu_n1"] == [], type(enc).__name__
        assert a["bits"]["bool_nk"] == [], (
            f"{type(enc).__name__} enabled_bits_vec materializes the "
            "dense [N, K] bool mask"
        )
        assert a["step"]["gathers"] <= max_step_gathers, (
            type(enc).__name__, a["step"]["gathers"]
        )


def test_codegen_shapes_hand_2pc_full_bar():
    """The hand 2pc encoding meets the FULL compiled-codegen bar
    (round 7: PR 2 landed its SparseEncodedModel interface but only
    pinned the gather counts): its step path is pure slot arithmetic
    — zero gathers, zero [N, 1] ALU, zero stack-of-scalars concats —
    so any future 2pc edit that reaches for a per-slot table or a
    lane-stacking concat fails here, not on a chip profile."""
    from stateright_tpu.models.two_phase_commit_tpu import (
        TwoPhaseSysEncoded,
    )

    a = _audit_enc(TwoPhaseSysEncoded(4))
    assert a["step"]["gathers"] == 0, a["step"]["gather_sites"]
    assert a["step"]["alu_n1"] == [], a["step"]["alu_n1_sites"]
    assert a["step"]["wide_concat_n1"] == 0
    assert a["bits"]["wide_concat_n1"] == 0
    assert a["mask"]["bool_nk"] != [], (
        "the mask path's dense bool[K] view is its contract — if this "
        "disappears the audit is tracing the wrong function"
    )


def test_engine_path_no_dense_mask_hand_2pc():
    """Round-7 calibration extension: the same engine-path audit the
    paxos and compiled-ABD encodings are pinned by, for the hand 2pc
    encoding (PR 2 gave it enabled_bits_vec; nothing pinned the
    engine path it feeds). K=22 packs into a single uint32 word, so
    this also covers the L=1 scalar-word lane of the shared
    pipeline."""
    from stateright_tpu.models.two_phase_commit_tpu import (
        TwoPhaseSysEncoded,
    )

    enc = TwoPhaseSysEncoded(4)
    s = _audit_engine_pair_pipeline(enc)
    assert s["bool_nk"] == [], (
        "dense [F, K] bool on the hand-2pc engine path",
        s["bool_nk_sites"],
    )
    assert s["gathers"] == 0, s["gather_sites"]


def test_bitmask_helpers_roundtrip():
    rng = np.random.default_rng(7)
    for k in (1, 31, 32, 33, 110, 257):
        m = rng.random((5, k)) < 0.4
        words = np.asarray(mask_to_words(jnp, jnp.asarray(m)))
        assert words.shape == (5, mask_words(k))
        back = np.asarray(words_to_mask(jnp, jnp.asarray(words), k))
        assert (back == m).all()
        cnt = np.asarray(popcount_words(jnp, jnp.asarray(words)))
        assert (cnt == m.sum(axis=1)).all()
    # bit_select against direct indexing, across word boundaries.
    flags = (rng.random(77) < 0.5).tolist()
    words = pack_bits_host(flags)
    idx = jnp.arange(77, dtype=jnp.uint32)
    got = np.asarray(
        jax.vmap(lambda i: bit_select(jnp, words, i))(idx)
    )
    assert (got == np.array(flags)).all()


def test_word_class_builders():
    """The round-6 word-level guard builders: slot_mask_host packs
    classes, or_class_words ORs them under traced conditions,
    select_words_host picks table rows by a traced field — all
    gather-free and equal to the dense reference construction."""
    from stateright_tpu.ops.bitmask import (
        or_class_words,
        select_words_host,
        slot_mask_host,
    )

    K = 70
    L = mask_words(K)
    rng = np.random.default_rng(3)
    classes_host = [
        sorted(rng.choice(K, size=rng.integers(0, 9), replace=False))
        for _ in range(6)
    ]
    masks = [slot_mask_host(K, cls) for cls in classes_host]
    table = [slot_mask_host(K, cls) for cls in classes_host[:4]]

    def build(conds, sel):
        import jax.numpy as jnp  # noqa: F811

        out = or_class_words(
            jnp,
            [(conds[i], masks[i]) for i in range(len(masks))],
            L,
        )
        return out | select_words_host(jnp, table, sel)

    for trial in range(8):
        conds = rng.random(len(masks)) < 0.5
        sel = int(rng.integers(0, len(table)))
        got = np.asarray(
            jax.jit(build)(jnp.asarray(conds), jnp.uint32(sel))
        )
        want = np.zeros(L, np.uint64)
        for i, on in enumerate(conds):
            if on:
                want |= np.array(masks[i], np.uint64)
        want |= np.array(table[sel], np.uint64)
        assert (got == want.astype(np.uint32)).all()
    # The builders themselves trace gather-free.
    jx = jax.make_jaxpr(build)(
        jnp.zeros(len(masks), bool), jnp.uint32(0)
    )
    assert not any(
        "gather" in eq.primitive.name for eq in jx.jaxpr.eqns
    )
