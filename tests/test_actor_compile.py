"""The actor→encoding compiler (actor/compile.py), proven by
REGENERATING workloads that have hand encodings or reference-pinned
counts and diffing results (VERDICT r2 item 2 / SURVEY §7 step 5):

* ping-pong: 14 (lossy dup, max 1), 4,094 (lossy dup, max 5, boundary),
  11 (lossless nondup, max 5) — reference actor/model.rs:688, 847, 887
* single-copy register 2c/1s: 93 — examples/single-copy-register.rs:110,
  diffed against the hand encoding models/single_copy_register_tpu.py
* ABD linearizable register 2c/2s: 544 —
  examples/linearizable-register.rs:286 (no hand encoding exists: this
  is "a new actor workload gets check-tpu with zero hand-written
  device code")

All device runs go through spawn_tpu_sortmerge on the CPU mesh and are
compared engine-to-host on unique counts AND discovered property sets.
"""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.actor.compile import compile_actor_model
from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
from stateright_tpu.models.ping_pong import PingPongCfg, ping_pong_model


def ping_pong_specs(cfg):
    counts = lambda ctx: ctx.actor_values(lambda i, s: s)

    def in_le_out(ctx, jnp):
        return ctx.history_value(lambda h: int(h[0] <= h[1])) == 1

    def out_le_in1(ctx, jnp):
        return ctx.history_value(lambda h: int(h[1] <= h[0] + 1)) == 1

    return dict(
        properties={
            "delta within 1": lambda ctx, jnp: (
                jnp.max(counts(ctx)) - jnp.min(counts(ctx)) <= 1
            ),
            "can reach max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat
            ),
            "must reach max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat
            ),
            "must exceed max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat + 1
            ),
            "#in <= #out": in_le_out,
            "#out <= #in + 1": out_le_in1,
        },
        boundary=lambda ctx, jnp: jnp.all(counts(ctx) <= cfg.max_nat),
        closure_actor_bound=lambda i, s: s <= cfg.max_nat,
        # History counters only advance on non-no-op deliveries, which
        # the actor-state bound caps at max_nat+1 per actor; beyond
        # that the (in, out) pairs only occur outside the boundary.
        closure_history_bound=lambda h: max(h) <= 2 * (cfg.max_nat + 2),
    )


def spawn_compiled(model, enc, **kw):
    kw.setdefault("capacity", 1 << 13)
    kw.setdefault("frontier_capacity", 1 << 10)
    kw.setdefault("cand_capacity", 1 << 12)
    return model.checker().spawn_tpu_sortmerge(encoded=enc, **kw)


def assert_matches_host(model, enc, expected_unique):
    host = model.checker().spawn_bfs().join()
    assert host.unique_state_count() == expected_unique
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == expected_unique
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    # Discovery paths replay through the host model (materializing a
    # Path already replays the trace — the differential check that the
    # compiled step_vec agrees with the actor handlers). The last state
    # must witness the discovery: satisfy a sometimes, violate an
    # always; an eventually counterexample is a terminal path on which
    # the condition never held.
    from stateright_tpu.model import Expectation

    for name, path in tpu.discoveries().items():
        prop = model.property_by_name(name)
        if prop.expectation == Expectation.SOMETIMES:
            assert prop.condition(model, path.last_state())
        elif prop.expectation == Expectation.ALWAYS:
            assert not prop.condition(model, path.last_state())
        else:
            assert all(
                not prop.condition(model, s) for s, _ in path.steps
            )
    return host, tpu


@pytest.mark.parametrize(
    "cfg_kw,lossy,network,expected",
    [
        (dict(max_nat=1, maintains_history=True), True, None, 14),
        (dict(max_nat=5, maintains_history=True), True, None, 4094),
        (
            dict(max_nat=5, maintains_history=True),
            False,
            Network.new_unordered_nonduplicating(),
            11,
        ),
    ],
)
def test_ping_pong_regenerated(cfg_kw, lossy, network, expected):
    cfg = PingPongCfg(**cfg_kw)
    model = ping_pong_model(cfg)
    if network is not None:
        model.init_network(network)
    model.set_lossy_network(lossy)
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    assert_matches_host(model, enc, expected)


def test_ping_pong_crashes_regenerated():
    """Crash slots: lossless nondup max 2 with one allowed crash."""
    cfg = PingPongCfg(max_nat=2, maintains_history=True)
    model = (
        ping_pong_model(cfg)
        .init_network(Network.new_unordered_nonduplicating())
        .set_max_crashes(1)
    )
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    host = model.checker().spawn_bfs().join()
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


def register_specs(default_value):
    def linearizable(ctx, jnp):
        return (
            ctx.history_value(
                lambda h: int(h.serialized_history() is not None)
            )
            == 1
        )

    def value_chosen(ctx, jnp):
        return ctx.network_any(
            lambda env: isinstance(env.msg, GetOk)
            and env.msg.value != default_value
        )

    return {"linearizable": linearizable, "value chosen": value_chosen}


def test_single_copy_regenerated_matches_hand_encoding():
    from stateright_tpu.actor.register import DEFAULT_VALUE
    from stateright_tpu.models.single_copy_register import (
        SingleCopyRegisterCfg,
        single_copy_register_model,
    )
    from stateright_tpu.models.single_copy_register_tpu import (
        SingleCopyEncoded,
    )

    cfg = SingleCopyRegisterCfg(client_count=2)
    model = single_copy_register_model(cfg)
    enc = compile_actor_model(
        model,
        properties=register_specs(DEFAULT_VALUE),
        # Each client performs at most put_count+1 operations.
        closure_history_bound=lambda h: len(h)
        <= cfg.client_count * (cfg.put_count + 1),
    )
    host, tpu = assert_matches_host(model, enc, 93)

    # Diff against the HAND encoding: same counts, same discoveries.
    hand = (
        single_copy_register_model(cfg)
        .checker()
        .spawn_tpu_sortmerge(
            encoded=SingleCopyEncoded(cfg),
            capacity=1 << 10,
            frontier_capacity=256,
            cand_capacity=1 << 11,
        )
        .join()
    )
    assert hand.unique_state_count() == tpu.unique_state_count() == 93
    assert sorted(hand.discoveries()) == sorted(tpu.discoveries())


def test_abd_regenerated_544():
    """ABD gets check-tpu with zero hand-written device code
    (examples/linearizable-register.rs:286 pins 544 states)."""
    from stateright_tpu.actor.register import DEFAULT_VALUE
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=2, server_count=2)
    model = abd_model(cfg)
    # ABD's logical clocks are bounded only by system reachability, so
    # the overapprox closure diverges (like paxos ballots) — harvest
    # from host exploration instead.
    enc = compile_actor_model(
        model,
        properties=register_specs(DEFAULT_VALUE),
        closure="reachable",
    )
    assert_matches_host(model, enc, 544)


def test_compiler_refuses_ordered_network():
    cfg = PingPongCfg(max_nat=1)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    with pytest.raises(ValueError, match="ordered"):
        compile_actor_model(model, **ping_pong_specs(cfg))


def test_compiler_requires_specs_for_all_properties():
    cfg = PingPongCfg(max_nat=1)
    model = ping_pong_model(cfg)
    with pytest.raises(ValueError, match="no device spec"):
        compile_actor_model(model, properties={})


def test_closure_divergence_detected():
    cfg = PingPongCfg(max_nat=5)
    model = ping_pong_model(cfg)
    specs = ping_pong_specs(cfg)
    specs.pop("closure_actor_bound")  # counters now unbounded
    with pytest.raises(RuntimeError, match="closure"):
        compile_actor_model(model, max_domain=64, **specs)


def test_count_bound_overflow_raises():
    """A model with finite component domains but unbounded envelope
    multiplicity must fail loudly when the device prunes a successor at
    the implicit 128-count bound (ADVICE r3, medium) — not report a
    clean, silently truncated 'verified' space."""
    from stateright_tpu.actor import Actor, ActorModel, Network

    class Flooder(Actor):
        def on_start(self, id, out):
            out.send(id, "go")
            return 0

        def on_msg(self, id, state, src, msg, out):
            # Consume one "go", emit two: multiplicity diverges while
            # the local state and envelope universe stay singletons.
            out.send(id, "go")
            out.send(id, "go")

    model = (
        ActorModel(cfg=None)
        .actor(Flooder())
        .init_network(Network.new_unordered_nonduplicating())
    )
    enc = compile_actor_model(model, properties={})
    checker = spawn_compiled(
        model, enc,
        capacity=1 << 9, frontier_capacity=1 << 5,
        cand_capacity=1 << 7, waves_per_sync=32,
    )
    with pytest.raises(RuntimeError, match="encoding-bound overflow"):
        checker.join()


def test_reachable_mode_propagates_handler_errors():
    """closure='reachable' harvests only reachable (state, envelope)
    pairs, so a raising handler is a genuine model bug and must fail
    the compile (ADVICE r3) — overapprox mode still records a no-op."""
    from stateright_tpu.actor import Actor, ActorModel

    class Boom(Actor):
        def on_start(self, id, out):
            out.send(id, "go")
            return 0

        def on_msg(self, id, state, src, msg, out):
            raise KeyError("handler bug")

    model = ActorModel(cfg=None).actor(Boom())
    with pytest.raises(RuntimeError, match="on_msg raised on a reachable"):
        compile_actor_model(model, properties={}, closure="reachable")
    # Overapprox mode keeps the lenient no-op treatment.
    enc = compile_actor_model(model, properties={})
    assert enc.width >= 1
