"""The actor→encoding compiler (actor/compile.py), proven by
REGENERATING workloads that have hand encodings or reference-pinned
counts and diffing results (VERDICT r2 item 2 / SURVEY §7 step 5):

* ping-pong: 14 (lossy dup, max 1), 4,094 (lossy dup, max 5, boundary),
  11 (lossless nondup, max 5) — reference actor/model.rs:688, 847, 887
* single-copy register 2c/1s: 93 — examples/single-copy-register.rs:110,
  diffed against the hand encoding models/single_copy_register_tpu.py
* ABD linearizable register 2c/2s: 544 —
  examples/linearizable-register.rs:286 (no hand encoding exists: this
  is "a new actor workload gets check-tpu with zero hand-written
  device code")

All device runs go through spawn_tpu_sortmerge on the CPU mesh and are
compared engine-to-host on unique counts AND discovered property sets.
"""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.actor.compile import compile_actor_model
from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
from stateright_tpu.models.ping_pong import (
    PingPongCfg,
    ping_pong_device_specs as ping_pong_specs,  # noqa: F401 — re-export
    ping_pong_model,
)


def spawn_compiled(model, enc, **kw):
    kw.setdefault("capacity", 1 << 13)
    kw.setdefault("frontier_capacity", 1 << 10)
    # Sparse dispatch budgets ENABLED pairs, which (unlike the dense
    # valid count) includes successors the boundary later prunes —
    # size for the larger of the two.
    kw.setdefault("cand_capacity", 1 << 14)
    return model.checker().spawn_tpu_sortmerge(encoded=enc, **kw)


def assert_matches_host(model, enc, expected_unique):
    host = model.checker().spawn_bfs().join()
    assert host.unique_state_count() == expected_unique
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == expected_unique
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    # Discovery paths replay through the host model (materializing a
    # Path already replays the trace — the differential check that the
    # compiled step_vec agrees with the actor handlers). The last state
    # must witness the discovery: satisfy a sometimes, violate an
    # always; an eventually counterexample is a terminal path on which
    # the condition never held.
    from stateright_tpu.model import Expectation

    for name, path in tpu.discoveries().items():
        prop = model.property_by_name(name)
        if prop.expectation == Expectation.SOMETIMES:
            assert prop.condition(model, path.last_state())
        elif prop.expectation == Expectation.ALWAYS:
            assert not prop.condition(model, path.last_state())
        else:
            assert all(
                not prop.condition(model, s) for s, _ in path.steps
            )
    return host, tpu


@pytest.mark.parametrize(
    "cfg_kw,lossy,network,expected",
    [
        (dict(max_nat=1, maintains_history=True), True, None, 14),
        (dict(max_nat=5, maintains_history=True), True, None, 4094),
        (
            dict(max_nat=5, maintains_history=True),
            False,
            Network.new_unordered_nonduplicating(),
            11,
        ),
    ],
)
def test_ping_pong_regenerated(cfg_kw, lossy, network, expected):
    cfg = PingPongCfg(**cfg_kw)
    model = ping_pong_model(cfg)
    if network is not None:
        model.init_network(network)
    model.set_lossy_network(lossy)
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    assert_matches_host(model, enc, expected)


def test_ping_pong_crashes_regenerated():
    """Crash slots: lossless nondup max 2 with one allowed crash."""
    cfg = PingPongCfg(max_nat=2, maintains_history=True)
    model = (
        ping_pong_model(cfg)
        .init_network(Network.new_unordered_nonduplicating())
        .set_max_crashes(1)
    )
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    host = model.checker().spawn_bfs().join()
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


# The register-family device specs now live in the library
# (actor/register.py) so models can compile themselves; re-exported
# here for the existing test call sites.
from stateright_tpu.actor.register import register_specs  # noqa: E402


def test_single_copy_regenerated_matches_hand_encoding():
    from stateright_tpu.actor.register import DEFAULT_VALUE
    from stateright_tpu.models.single_copy_register import (
        SingleCopyRegisterCfg,
        single_copy_register_model,
    )
    from stateright_tpu.models.single_copy_register_tpu import (
        SingleCopyEncoded,
    )

    cfg = SingleCopyRegisterCfg(client_count=2)
    model = single_copy_register_model(cfg)
    enc = compile_actor_model(
        model,
        properties=register_specs(DEFAULT_VALUE),
        # Each client performs at most put_count+1 operations.
        closure_history_bound=lambda h: len(h)
        <= cfg.client_count * (cfg.put_count + 1),
    )
    host, tpu = assert_matches_host(model, enc, 93)

    # Diff against the HAND encoding: same counts, same discoveries.
    hand = (
        single_copy_register_model(cfg)
        .checker()
        .spawn_tpu_sortmerge(
            encoded=SingleCopyEncoded(cfg),
            capacity=1 << 10,
            frontier_capacity=256,
            cand_capacity=1 << 11,
        )
        .join()
    )
    assert hand.unique_state_count() == tpu.unique_state_count() == 93
    assert sorted(hand.discoveries()) == sorted(tpu.discoveries())


def test_abd_regenerated_544():
    """ABD gets check-tpu with zero hand-written device code
    (examples/linearizable-register.rs:286 pins 544 states)."""
    from stateright_tpu.actor.register import DEFAULT_VALUE
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=2, server_count=2)
    model = abd_model(cfg)
    # ABD's logical clocks are bounded only by system reachability, so
    # the overapprox closure diverges (like paxos ballots) — harvest
    # from host exploration instead.
    enc = compile_actor_model(
        model,
        properties=register_specs(DEFAULT_VALUE),
        closure="reachable",
    )
    assert_matches_host(model, enc, 544)


def test_compiler_ordered_requires_reachable():
    """Ordered networks need the harvested queue bounds of reachable
    mode; overapprox mode fails loudly (see the Limits docstring)."""
    cfg = PingPongCfg(max_nat=1)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    with pytest.raises(ValueError, match="reachable"):
        compile_actor_model(model, **ping_pong_specs(cfg))


def test_compiler_requires_specs_for_all_properties():
    cfg = PingPongCfg(max_nat=1)
    model = ping_pong_model(cfg)
    with pytest.raises(ValueError, match="no device spec"):
        compile_actor_model(model, properties={})


def test_closure_divergence_detected():
    cfg = PingPongCfg(max_nat=5)
    model = ping_pong_model(cfg)
    specs = ping_pong_specs(cfg)
    specs.pop("closure_actor_bound")  # counters now unbounded
    with pytest.raises(RuntimeError, match="closure"):
        compile_actor_model(model, max_domain=64, **specs)


def test_count_bound_overflow_raises():
    """A model with finite component domains but unbounded envelope
    multiplicity must fail loudly when the device prunes a successor at
    the implicit 128-count bound (ADVICE r3, medium) — not report a
    clean, silently truncated 'verified' space."""
    from stateright_tpu.actor import Actor, ActorModel, Network

    class Flooder(Actor):
        def on_start(self, id, out):
            out.send(id, "go")
            return 0

        def on_msg(self, id, state, src, msg, out):
            # Consume one "go", emit two: multiplicity diverges while
            # the local state and envelope universe stay singletons.
            out.send(id, "go")
            out.send(id, "go")

    model = (
        ActorModel(cfg=None)
        .actor(Flooder())
        .init_network(Network.new_unordered_nonduplicating())
    )
    enc = compile_actor_model(model, properties={})
    checker = spawn_compiled(
        model, enc,
        capacity=1 << 9, frontier_capacity=1 << 5,
        cand_capacity=1 << 7, waves_per_sync=32,
    )
    with pytest.raises(RuntimeError, match="encoding-bound overflow"):
        checker.join()


def test_reachable_mode_propagates_handler_errors():
    """closure='reachable' harvests only reachable (state, envelope)
    pairs, so a raising handler is a genuine model bug and must fail
    the compile (ADVICE r3) — overapprox mode still records a no-op."""
    from stateright_tpu.actor import Actor, ActorModel

    class Boom(Actor):
        def on_start(self, id, out):
            out.send(id, "go")
            return 0

        def on_msg(self, id, state, src, msg, out):
            raise KeyError("handler bug")

    model = ActorModel(cfg=None).actor(Boom())
    with pytest.raises(RuntimeError, match="on_msg raised on a reachable"):
        compile_actor_model(model, properties={}, closure="reachable")
    # Overapprox mode keeps the lenient no-op treatment.
    enc = compile_actor_model(model, properties={})
    assert enc.width >= 1


def _sparse_contract_check(enc, max_states=20000):
    """Pin the SparseEncodedModel contract for a compiled encoding over
    every reachable state: ``enabled & ~trunc`` equals the dense
    validity, and ``step_slot_vec`` reproduces ``step_vec``'s successor
    on every enabled, non-truncated pair."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from collections import deque

    model = enc.host_model
    seen = {}
    q = deque()
    for s in model.init_states():
        seen[tuple(enc.encode(s).tolist())] = s
        q.append(s)
    while q:
        s = q.popleft()
        for a in model.actions(s):
            n = model.next_state(s, a)
            if n is None or not model.within_boundary(n):
                continue
            key = tuple(enc.encode(n).tolist())
            if key not in seen:
                assert len(seen) < max_states
                seen[key] = n
                q.append(n)
    vecs = jnp.asarray(np.array(sorted(seen), dtype=np.uint32))
    succs, valid, _ = (
        np.asarray(a) for a in jax.jit(jax.vmap(enc.step_vec))(vecs)
    )
    mask = np.asarray(jax.jit(jax.vmap(enc.enabled_mask_vec))(vecs))
    rows, slots = np.nonzero(mask)
    sp, ptr, hard = (
        np.asarray(a)
        for a in jax.jit(jax.vmap(enc.step_slot_vec))(
            vecs[jnp.asarray(rows)],
            jnp.asarray(slots.astype(np.uint32)),
        )
    )
    bad = ptr | hard
    eff = mask.copy()
    eff[rows[bad], slots[bad]] = False
    assert (eff == valid).all(), "enabled & ~trunc diverges from dense"
    ok = ~bad
    assert (sp[ok] == succs[rows[ok], slots[ok]]).all(), (
        "step_slot_vec diverges from step_vec"
    )
    return len(seen)


@pytest.mark.parametrize(
    "cfg_kw,lossy,network,expected",
    [
        (dict(max_nat=1), True, None, 14),           # deliver+drop, dup
        (dict(max_nat=5), False, "nondup", 11),      # deliver, nondup
        (dict(max_nat=2), True, "nondup", None),     # drop, NONDUP dec
    ],
)
def test_compiled_sparse_contract_ping_pong(cfg_kw, lossy, network,
                                            expected):
    cfg = PingPongCfg(maintains_history=True, **cfg_kw)
    model = ping_pong_model(cfg).set_lossy_network(lossy)
    if network == "nondup":
        model = model.init_network(Network.new_unordered_nonduplicating())
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    if expected is None:
        expected = (
            model.checker().spawn_bfs().join().unique_state_count()
        )
    assert _sparse_contract_check(enc) == expected


def test_compiled_sparse_contract_crashes_and_timers():
    """Crash and timeout slots through the sparse tables: a one-actor
    timer loop with crashes."""
    from stateright_tpu.actor import Actor, ActorModel
    from stateright_tpu.model import Expectation

    class Ticker(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", (1.0, 2.0))
            return 0

        def on_msg(self, id, state, src, msg, out):
            pass

        def on_timeout(self, id, state, timer, out):
            if state.value < 3:
                state.set(state.value + 1)
                out.set_timer("tick", (1.0, 2.0))

    model = (
        ActorModel(cfg=None)
        .actor(Ticker())
        .actor(Ticker())
        .set_max_crashes(1)
        .property(
            Expectation.ALWAYS, "counts bounded",
            lambda cfg, s: all(a <= 3 for a in s.actor_states),
        )
    )
    enc = compile_actor_model(
        model,
        properties={
            "counts bounded": lambda ctx, jnp: jnp.all(
                ctx.actor_values(lambda i, s: s) <= 3
            ),
        },
    )
    n = _sparse_contract_check(enc)
    host = model.checker().spawn_bfs().join()
    assert n == host.unique_state_count()
    sp = spawn_compiled(model, enc, sparse=True, pair_width=8).join()
    assert sp.unique_state_count() == n
    assert sorted(sp.discoveries()) == sorted(host.discoveries())


def test_compiled_sparse_engine_matches_dense():
    """Ping-pong 4,094 (lossy dup, boundary) through the sparse engine:
    identical count and property set as dense — exercises the
    boundary-aware sparse path (terminal scatter-back)."""
    cfg = PingPongCfg(maintains_history=True, max_nat=5)
    model = ping_pong_model(cfg).set_lossy_network(True)
    enc = compile_actor_model(model, **ping_pong_specs(cfg))
    dense = spawn_compiled(model, enc, sparse=False).join()
    sp = spawn_compiled(model, enc, sparse=True, pair_width=16).join()
    assert sp.unique_state_count() == dense.unique_state_count() == 4094
    assert sorted(sp.discoveries()) == sorted(dense.discoveries())


def test_abd_sharded_sortmerge_fingerprint_only():
    """Compiler × sharding: the compiled ABD encoding through the
    sharded sort-merge engine (2 CPU-mesh shards) — the product's core
    composition (VERDICT r3 weak #7). The 544 count and the property
    set must match the host.

    Fingerprint-only on the CPU mesh: with track_paths=True this exact
    configuration (compiled encoding × sharded engine) hits an XLA:CPU
    thunk-runtime livelock (same bug family as the concatenated-payload
    gather livelock bisected in the single-chip engine, PERF.md
    §gathers; hand encodings with paths run fine on the same mesh —
    see test_sharded_sparse_paxos_with_paths). The full compiled ×
    sharded × paths composition is covered on real TPU by
    test_abd_sharded_paths_on_tpu below."""
    from stateright_tpu.actor.register import DEFAULT_VALUE
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=2, server_count=2)
    model = abd_model(cfg)
    enc = compile_actor_model(
        model,
        properties=register_specs(DEFAULT_VALUE),
        closure="reachable",
    )
    host = model.checker().spawn_bfs().join()
    sharded = (
        model.checker()
        .spawn_tpu_sharded_sortmerge(
            encoded=enc,
            n_shards=2,
            capacity=1 << 10,
            frontier_capacity=1 << 9,
            cand_capacity=1 << 11,
            track_paths=False,
        )
        .join()
    )
    assert sharded.unique_state_count() == 544
    assert sharded.discovered_property_names() == set(host.discoveries())


def test_abd_sharded_paths_on_tpu():
    """Compiler × sharding × PATHS (VERDICT r4 weak #4 / item 6): the
    compiled ABD encoding through spawn_tpu_sharded_sortmerge with
    track_paths=True, a replayed discovery path included. Runs on the
    real TPU only (single-device mesh) — on XLA:CPU this composition
    livelocks the thunk runtime (see the fingerprint-only test above).
    Executed on TPU v5 lite (axon) 2026-07-31: 544 states, 14s
    end-to-end including compile, 11-action 'value chosen' path."""
    import jax

    if jax.default_backend() == "cpu":
        pytest.skip("XLA:CPU thunk-runtime livelock; TPU-only")
    import numpy as np
    from jax.sharding import Mesh

    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    model = abd_model(AbdModelCfg(client_count=2, server_count=2))
    enc = model.to_encoded()
    host = (
        abd_model(AbdModelCfg(client_count=2, server_count=2))
        .checker()
        .spawn_bfs()
        .join()
    )
    c = (
        model.checker()
        .spawn_tpu_sharded_sortmerge(
            encoded=enc,
            mesh=mesh,
            capacity=1 << 10,
            frontier_capacity=1 << 9,
            cand_capacity=1 << 11,
            track_paths=True,
        )
        .join()
    )
    assert c.unique_state_count() == 544 == host.unique_state_count()
    assert sorted(c.discoveries()) == sorted(host.discoveries())
    p = c.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1


def test_compiled_ordered_ping_pong():
    """Ordered (FIFO) networks compile (VERDICT r3 missing #3):
    integer-queue channels, head-only delivery, the no-op-pop
    exception, and FIFO send appends — regenerated ping-pong matches
    host BFS state-for-state with replayed discovery paths, and the
    sparse contract holds exhaustively."""
    cfg = PingPongCfg(maintains_history=True, max_nat=3)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    enc = compile_actor_model(
        model, closure="reachable", **ping_pong_specs(cfg)
    )
    host = model.checker().spawn_bfs().join()
    assert_matches_host(model, enc, host.unique_state_count())
    assert _sparse_contract_check(enc) == host.unique_state_count()


def test_compiled_ordered_abd():
    """`linearizable-register check-tpu 2 ordered` (BASELINE.md:32,
    bench.sh:33): the compiled ABD encoding over FIFO channels matches
    host DFS count and property set, with a replayed path."""
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=2, server_count=2)
    model = abd_model(cfg, Network.new_ordered())
    host = model.checker().spawn_dfs().join()
    enc = model.to_encoded()
    tpu = spawn_compiled(model, enc, capacity=1 << 14,
                         frontier_capacity=1 << 11).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    p = tpu.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1


def test_compiled_ordered_overapprox_declared_bounds():
    """Ordered networks under bounded overapproximation (VERDICT r4
    item 4): a DECLARED per-channel queue bound replaces the
    reachable-mode host exploration entirely — same count, property
    set, and replayable paths as the harvested-bounds compile."""
    cfg = PingPongCfg(maintains_history=True, max_nat=3)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    harvested = compile_actor_model(
        model, closure="reachable", **ping_pong_specs(cfg)
    )
    bounds = {
        (int(ch[0]), int(ch[1])): harvested.ch_q[ch]
        for ch in harvested.channels
    }
    enc = compile_actor_model(
        model,
        closure="overapprox",
        closure_queue_bound=bounds,
        **ping_pong_specs(cfg),
    )
    assert enc.closure_mode == "overapprox"
    host = model.checker().spawn_bfs().join()
    assert_matches_host(model, enc, host.unique_state_count())
    # A uniform int bound works too (max(harvested, declared) rule
    # keeps the layout sound even when generous).
    enc2 = compile_actor_model(
        model,
        closure="overapprox",
        closure_queue_bound=max(bounds.values()),
        **ping_pong_specs(cfg),
    )
    tpu = spawn_compiled(model, enc2, sparse=True).join()
    assert tpu.unique_state_count() == host.unique_state_count()


def test_compiled_ordered_overapprox_underdeclared_bound_is_loud():
    """An under-declared queue bound must raise the truncation flag,
    never silently verify a truncated space."""
    cfg = PingPongCfg(maintains_history=True, max_nat=3)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    enc = compile_actor_model(
        model,
        closure="overapprox",
        closure_queue_bound=1,
        **ping_pong_specs(cfg),
    )
    host = model.checker().spawn_bfs().join()
    try:
        c = spawn_compiled(model, enc).join()
    except RuntimeError as exc:
        assert "truncat" in str(exc) or "encoding-bound" in str(exc)
    else:
        # A bound of 1 may genuinely suffice for this protocol; the
        # test then degenerates to the agreement check.
        assert c.unique_state_count() == host.unique_state_count()


def test_compiled_ordered_rejects_unsupported_modes():
    cfg = PingPongCfg(max_nat=1)
    model = ping_pong_model(cfg).init_network(Network.new_ordered())
    with pytest.raises(ValueError, match="reachable"):
        compile_actor_model(model, **ping_pong_specs(cfg))
    lossy = (
        ping_pong_model(cfg)
        .init_network(Network.new_ordered())
        .set_lossy_network(True)
    )
    with pytest.raises(ValueError, match="lossy ordered"):
        compile_actor_model(
            lossy, closure="reachable", **ping_pong_specs(cfg)
        )


def test_abd_bounded_overapprox_default():
    """VERDICT r3 #5: ABD's default encoding mode is now bounded
    overapproximation — protocol bounds (clock <= writes, ops <=
    put_count+1, linearizable-expansion) close the component fixpoint
    WITHOUT any host exploration — and still reproduces the
    reference-pinned 544 with the host property set and a replayable
    path."""
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=2, server_count=2)
    model = abd_model(cfg)
    enc = model.to_encoded()
    assert enc.closure_mode == "overapprox"
    host = model.checker().spawn_bfs().join()
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == 544
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    p = tpu.discovery("value chosen")
    assert p is not None and len(p.actions()) >= 1


def test_abd_3clients_bounded_overapprox_compiles_and_agrees():
    """The scale story for bounded overapproximation (VERDICT r3 #5):
    at 3 clients the closure converges from protocol bounds alone (no
    host exploration — round 3's "reachable" mode would have explored
    all 35,009 system states at compile time), and the encoding agrees
    with the host on every successor of the shallow prefix. The FULL
    device run was executed on real TPU (round 4): 35,009 states,
    matching an independently-run host BFS."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from collections import deque

    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    cfg = AbdModelCfg(client_count=3, server_count=2)
    model = abd_model(cfg)
    enc = model.to_encoded()
    assert enc.closure_mode == "overapprox"
    # Shallow differential: device successors == host successors.
    seen = {}
    q = deque()
    for s in model.init_states():
        seen[tuple(enc.encode(s).tolist())] = s
        q.append((s, 0))
    while q:
        s, d = q.popleft()
        if d >= 3:
            continue
        for n in model.next_states(s):
            k = tuple(enc.encode(n).tolist())
            if k not in seen:
                seen[k] = n
                q.append((n, d + 1))
    vecs = jnp.asarray(np.array(sorted(seen), dtype=np.uint32))
    mask = np.asarray(jax.jit(jax.vmap(enc.enabled_mask_vec))(vecs))
    rows, slots = np.nonzero(mask)
    sp, ptr, hard = (
        np.asarray(a)
        for a in jax.jit(jax.vmap(enc.step_slot_vec))(
            vecs[jnp.asarray(rows)],
            jnp.asarray(slots.astype(np.uint32)),
        )
    )
    assert not ptr.any() and not hard.any()
    got = {}
    for j in range(len(rows)):
        got.setdefault(int(rows[j]), set()).add(tuple(sp[j].tolist()))
    keys = sorted(seen)
    for i, k in enumerate(keys):
        host_succ = {
            tuple(enc.encode(n).tolist())
            for n in model.next_states(seen[k])
        }
        assert got.get(i, set()) == host_succ


def test_compiled_ordered_abd_3s_depth_differential():
    """The bench lane `abd 2c/3s ordered` (driver family
    `linearizable-register check N ordered`, BASELINE.md:32): the
    overapprox-compiled FIFO encoding matches host BFS state-for-state
    at a bounded depth, pinning the encoding semantics the full
    1,212,979-state device run (bench.py; reproduced across runs on
    real TPU, round 5) builds on. Depth 10 (1,066 states; was 7/171,
    ADVICE r5): encoding bugs that first manifest past the shallow
    prefix — queue-depth interleavings, second-round timestamps —
    fail here instead of moving the bench expectation."""
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    def mk():
        return abd_model(
            AbdModelCfg(client_count=2, server_count=3),
            Network.new_ordered(),
        )

    host = mk().checker().target_max_depth(10).spawn_bfs().join()
    assert host.unique_state_count() == 1066
    m = mk()
    tpu = (
        m.checker()
        .target_max_depth(10)
        .spawn_tpu_sortmerge(
            encoded=m.to_encoded(),
            capacity=1 << 13,
            frontier_capacity=1 << 11,
            cand_capacity=1 << 13,
            track_paths=False,
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.discovered_property_names() == set(host.discoveries())


@pytest.mark.slow
@pytest.mark.skipif(
    "STPU_EXHAUSTIVE" not in __import__("os").environ,
    reason="~hour-scale host DFS (~1.2M states at host rates); "
    "run with STPU_EXHAUSTIVE=1 (verified 2026-08-03: 1,212,979, "
    "only 'value chosen' — PERF.md §counts)",
)
def test_abd_ordered_2c3s_exhaustive_host_pin():
    """Independent exhaustive verification of the ordered BENCH lane's
    headline count (VERDICT r5 item 5): host DFS explores the full
    `abd 2c/3s ordered` space with no device involvement and must
    report exactly 1,212,979 unique states with only 'value chosen'
    discovered — so the count no longer rests on a single engine
    configuration plus depth-prefix differentials."""
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    ck = (
        abd_model(
            AbdModelCfg(client_count=2, server_count=3),
            Network.new_ordered(),
        )
        .checker()
        .spawn_dfs()
        .join()
    )
    assert ck.unique_state_count() == 1212979
    assert sorted(ck.discoveries()) == ["value chosen"]


def test_compiled_2pc_actors_matches_host():
    """The actor-model 2pc (models/two_phase_commit_actors.py — the
    registry's compiled-2pc fixture, ROADMAP direction 5) through the
    compiler: count + discovery parity with host BFS, and the
    consistency property holds. Doubles as the regression test for
    the history-table sentinel fix: this model is history-FREE
    (init_history=None), and the old `.get(key) is not None` lookup
    read the legitimate None history value as "un-harvested",
    hard-truncating every delivery on the first wave."""
    from stateright_tpu.models.two_phase_commit_actors import (
        two_phase_actor_device_specs,
        two_phase_actor_model,
    )

    model = two_phase_actor_model(2)
    enc = compile_actor_model(
        model, **two_phase_actor_device_specs(2)
    )
    assert_matches_host(model, enc, 306)


def test_compiled_paxos_matches_host():
    """The compiled paxos encoding (models/paxos.py
    paxos_compiled_encoded — the registry's compiled-paxos fixture):
    the actor paxos model through the compiler in reachable mode,
    count + discovery parity with host BFS at the registry config."""
    from stateright_tpu.models.paxos import (
        PaxosModelCfg,
        paxos_compiled_encoded,
        paxos_model,
    )

    cfg = PaxosModelCfg(client_count=1, server_count=2, put_count=1)
    model = paxos_model(cfg)
    enc = paxos_compiled_encoded(cfg)
    host = model.checker().spawn_bfs().join()
    tpu = spawn_compiled(model, enc).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()
