"""Consistency semantics: specs, testers, and the single-copy example.

Pinned ground truth: single-copy register 2 clients / 1 server = 93
unique states (reference examples/single-copy-register.rs:110);
2 clients / 2 servers is not linearizable.
"""

from stateright_tpu.semantics import (
    Len,
    LenOk,
    LinearizabilityTester,
    Pop,
    PopOk,
    Push,
    PushOk,
    ReadOk,
    ReadOp,
    Register,
    SequentialConsistencyTester,
    Vec,
    WORegister,
    WriteFail,
    WriteOk,
    WriteOp,
)
from stateright_tpu.models.single_copy_register import (
    SingleCopyRegisterCfg,
    single_copy_register_model,
)


# -- reference objects --------------------------------------------------


def test_register_spec():
    reg = Register(0)
    reg2, ret = reg.invoke(WriteOp(5))
    assert ret == WriteOk() and reg2.value == 5
    _, ret = reg2.invoke(ReadOp())
    assert ret == ReadOk(5)
    assert reg.is_valid_history([(WriteOp(1), WriteOk()), (ReadOp(), ReadOk(1))])
    assert not reg.is_valid_history([(WriteOp(1), WriteOk()), (ReadOp(), ReadOk(2))])


def test_write_once_register_spec():
    wo = WORegister()
    wo2, ret = wo.invoke(WriteOp("a"))
    assert ret == WriteOk()
    _, ret = wo2.invoke(WriteOp("b"))
    assert ret == WriteFail()
    _, ret = wo2.invoke(ReadOp())
    assert ret == ReadOk("a")


def test_vec_spec():
    v = Vec()
    assert v.is_valid_history(
        [
            (Push(1), PushOk()),
            (Push(2), PushOk()),
            (Len(), LenOk(2)),
            (Pop(), PopOk(2)),
            (Pop(), PopOk(1)),
            (Pop(), PopOk(None)),
        ]
    )
    assert not v.is_valid_history([(Pop(), PopOk(7))])


# -- linearizability ----------------------------------------------------


def test_linearizable_sequential_history():
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(5)).on_return(1, WriteOk())
    t = t.on_invoke(2, ReadOp()).on_return(2, ReadOk(5))
    assert t.is_consistent()
    assert t.serialized_history() == [
        (WriteOp(5), WriteOk()),
        (ReadOp(), ReadOk(5)),
    ]


def test_linearizability_rejects_stale_read_after_write():
    # Thread 2's read starts after thread 1's write completed, so it
    # must observe the new value (the real-time constraint).
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(5)).on_return(1, WriteOk())
    t = t.on_invoke(2, ReadOp()).on_return(2, ReadOk(0))
    assert not t.is_consistent()


def test_sequential_consistency_allows_stale_read():
    # The same history IS sequentially consistent: the read may be
    # ordered before the write.
    t = SequentialConsistencyTester(Register(0))
    t = t.on_invoke(1, WriteOp(5)).on_return(1, WriteOk())
    t = t.on_invoke(2, ReadOp()).on_return(2, ReadOk(0))
    assert t.is_consistent()


def test_concurrent_ops_may_linearize_either_way():
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(5))  # still in flight
    t = t.on_invoke(2, ReadOp()).on_return(2, ReadOk(5))  # sees it anyway
    assert t.is_consistent()

    t2 = LinearizabilityTester(Register(0))
    t2 = t2.on_invoke(1, WriteOp(5))
    t2 = t2.on_invoke(2, ReadOp()).on_return(2, ReadOk(0))  # or not
    assert t2.is_consistent()


def test_in_flight_op_may_stay_unlinearized():
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(5))  # never returns
    t = t.on_invoke(2, ReadOp()).on_return(2, ReadOk(0))
    assert t.is_consistent()


def test_double_invoke_invalidates_history():
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(1)).on_invoke(1, WriteOp(2))
    assert not t.is_consistent()


def test_return_without_invoke_invalidates_history():
    t = LinearizabilityTester(Register(0)).on_return(9, WriteOk())
    assert not t.is_consistent()


def test_program_order_enforced():
    # One thread's ops must linearize in program order.
    t = LinearizabilityTester(Register(0))
    t = t.on_invoke(1, WriteOp(1)).on_return(1, WriteOk())
    t = t.on_invoke(1, WriteOp(2)).on_return(1, WriteOk())
    t = t.on_invoke(1, ReadOp()).on_return(1, ReadOk(1))
    assert not t.is_consistent()


# -- end-to-end: single-copy register example ---------------------------


def test_single_copy_register_one_server_linearizable_93_states():
    checker = (
        single_copy_register_model(
            SingleCopyRegisterCfg(client_count=2, server_count=1)
        )
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 93


def test_single_copy_register_two_servers_not_linearizable():
    checker = (
        single_copy_register_model(
            SingleCopyRegisterCfg(client_count=2, server_count=2)
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_any_discovery("linearizable")
    checker.assert_any_discovery("value chosen")
