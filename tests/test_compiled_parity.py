"""Compiled-vs-hand parity gate (round 23): the hand encodings as
DIFFERENTIAL ORACLES for the compiled actor path.

The compiled 2pc lane runs the count-comparable system actor model
(models/two_phase_commit_actors.py two_phase_sys_actor_model — a
state-for-state bijection with the hand TwoPhaseSys model: dup-network
envelope bits <-> the append-only msgs bag, timer bits a function of
local state, atomic broadcast one bag entry), so the HAND engine lane
and the COMPILED engine lane explore the SAME pinned spaces (1,568 @
rm=4, 8,832 @ rm=5) and must agree on counts, verdicts, and replayable
counterexample paths.

The optimizer itself (actor/compile.py _optimize_codegen, on by
default) is pinned two ways: a naive-vs-optimized traced A/B through
the tools/trace_diff.py gate with ZERO per-wave counter divergence
(same encoding semantics — every counter, including candidates, must
match), and exhaustive emission differentials over every reachable
state x slot. Hand-vs-compiled traces align on the
encoding-INDEPENDENT counters (frontier rows, new states, unique
total); `candidates` legitimately differs — the compiled path prunes
no-op self-loops the hand encoding emits — and that asymmetry is
pinned too.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from stateright_tpu.model import Expectation

pytestmark = pytest.mark.parity

#: the pinned TwoPhaseSys spaces both lanes must reproduce
PINNED = {4: 1568, 5: 8832}


def _hand_checker(rm, **kw):
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    kw.setdefault("cand_capacity", "auto")
    return TwoPhaseSys(rm_count=rm).checker().spawn_tpu_sortmerge(**kw)


def _compiled_checker(rm, optimize=True, track_paths=False, **kw):
    from stateright_tpu.models.two_phase_commit_actors import (
        two_phase_sys_actor_model,
        two_phase_sys_compiled_encoded,
    )

    kw.setdefault("cand_capacity", "auto")
    return (
        two_phase_sys_actor_model(rm)
        .checker()
        .spawn_tpu_sortmerge(
            encoded=two_phase_sys_compiled_encoded(rm, optimize=optimize),
            track_paths=track_paths,
            **kw,
        )
    )


@pytest.mark.parametrize("rm", [4, 5])
def test_hand_oracle_counts_verdicts_paths(rm):
    """The hand lane is the oracle: the compiled lane must reproduce
    its unique count bit-identically, discover the same properties,
    and its counterexample paths must REPLAY through the actor model's
    host handlers with the right witness at the end."""
    from stateright_tpu.models.two_phase_commit_actors import (
        two_phase_sys_actor_model,
    )

    cap = dict(capacity=1 << (11 if rm == 4 else 14),
               frontier_capacity=1 << (9 if rm == 4 else 11))
    hand = _hand_checker(rm, track_paths=False, **cap).join()
    assert hand.unique_state_count() == PINNED[rm]

    comp = _compiled_checker(rm, track_paths=True, **cap).join()
    assert comp.unique_state_count() == PINNED[rm]
    assert sorted(comp.discoveries()) == sorted(
        hand.discovered_property_names()
    )

    # Replay: materializing a Path replays the trace through the host
    # actor handlers (the differential check that the compiled
    # step_slot_vec agrees with actor/base.py semantics); the last
    # state must witness the discovery.
    model = two_phase_sys_actor_model(rm)
    assert comp.discoveries(), "2pc always discovers its SOMETIMES"
    for name, path in comp.discoveries().items():
        prop = model.property_by_name(name)
        if prop.expectation == Expectation.SOMETIMES:
            assert prop.condition(model, path.last_state())
        else:
            assert not prop.condition(model, path.last_state())


def test_traced_ab_zero_divergence(tmp_path):
    """The optimizer A/B through the tools/trace_diff.py gate: a
    naive-compile (optimize=False) trace vs an optimized trace of the
    SAME encoding pipeline at rm=4 must show ZERO per-wave counter
    divergence — frontier rows, candidates, new states, and the
    running unique total all identical — and exit 0."""
    from stateright_tpu.telemetry import RunTracer, diff_traces

    cap = dict(capacity=1 << 11, frontier_capacity=1 << 9)
    ta = RunTracer()
    with ta.activate():
        a = _compiled_checker(4, optimize=False, **cap).join()
    tb = RunTracer()
    with tb.activate():
        b = _compiled_checker(4, optimize=True, **cap).join()
    assert a.unique_state_count() == b.unique_state_count() == 1568

    rep = diff_traces(ta.events, tb.events)
    assert rep["divergences"] == []

    # the same verdict through the CLI gate (artifact -> exit code)
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    pa.write_text("\n".join(json.dumps(e) for e in ta.events) + "\n")
    pb.write_text("\n".join(json.dumps(e) for e in tb.events) + "\n")
    proc = subprocess.run(
        [sys.executable, "tools/trace_diff.py", str(pa), str(pb),
         # timing is not under test here (two cold in-process runs);
         # the exit code must be decided by the counters alone
         "--threshold", "1000", "--min-sec", "1000"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WAVE DIVERGENCE" not in proc.stdout


def test_traced_hand_vs_compiled_independent_counters():
    """Hand vs compiled traces align on the encoding-INDEPENDENT wave
    counters — same spaces, same BFS layers: frontier rows, new
    states, unique totals identical every wave. `candidates` may
    differ (the compiled path prunes no-op self-loops the hand
    encoding emits) and is pinned to differ in that direction only:
    compiled <= hand on every wave."""
    from stateright_tpu.telemetry import RunTracer, diff_traces

    cap = dict(capacity=1 << 11, frontier_capacity=1 << 9)
    ta = RunTracer()
    with ta.activate():
        a = _hand_checker(4, track_paths=False, **cap).join()
    tb = RunTracer()
    with tb.activate():
        b = _compiled_checker(4, **cap).join()
    assert a.unique_state_count() == b.unique_state_count() == 1568

    rep = diff_traces(ta.events, tb.events)
    others = [d for d in rep["divergences"]
              if d["field"] != "candidates"]
    assert others == []
    for d in rep["divergences"]:
        assert d["field"] == "candidates" and d["b"] <= d["a"]


def test_optimizer_emission_differential_exhaustive():
    """Exhaustive naive-vs-optimized differential at rm=3: for EVERY
    reachable state and EVERY slot, the optimized enabled_bits_vec /
    step_slot_vec emissions agree bit-for-bit with the naive
    per-action codegen (bits words, dense mask view, successors on
    enabled pairs, trunc/hard flags)."""
    import jax
    import jax.numpy as jnp

    from stateright_tpu.actor.compile import compile_actor_model
    from stateright_tpu.encoding import normalize_step_slot_result
    from stateright_tpu.models.two_phase_commit_actors import (
        two_phase_sys_actor_model,
        two_phase_sys_device_specs,
    )

    m = two_phase_sys_actor_model(3)
    e1 = compile_actor_model(
        m, **two_phase_sys_device_specs(3), optimize=False
    )
    e2 = compile_actor_model(m, **two_phase_sys_device_specs(3))
    assert e1.codegen_opt is None and e2.codegen_opt is not None

    seen, frontier = set(), list(m.init_states())
    for s in frontier:
        seen.add(s)
    while frontier:
        nxt = []
        for s in frontier:
            for a in m.actions(s):
                t = m.next_state(s, a)
                if t is not None and t not in seen \
                        and m.within_boundary(t):
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
    states = sorted(seen, key=repr)
    assert len(states) == 288

    vecs = np.stack([e1.encode(s) for s in states])
    K = e1.max_actions
    SV = jnp.asarray(np.repeat(vecs, K, axis=0))
    SL = jnp.asarray(np.tile(np.arange(K, dtype=np.uint32),
                             len(states)))

    def run(e):
        bits = jax.jit(jax.vmap(e.enabled_bits_vec))(jnp.asarray(vecs))
        en = jax.jit(jax.vmap(e.enabled_mask_vec))(jnp.asarray(vecs))
        r = jax.jit(jax.vmap(e.step_slot_vec))(SV, SL)
        s, t, h = normalize_step_slot_result(r)
        bt = lambda x: np.broadcast_to(  # noqa: E731
            np.asarray(x), (len(states) * K,))
        return (np.asarray(bits), np.asarray(en).reshape(-1),
                np.asarray(s), bt(t), bt(h))

    b1, en, s1, t1, h1 = run(e1)
    b2, en2, s2, t2, h2 = run(e2)
    assert (b1 == b2).all()
    assert (en == en2).all()
    assert (s1[en] == s2[en]).all()
    assert (t1[en] == t2[en]).all() and (h1[en] == h2[en]).all()


def test_optimizer_plan_pins():
    """The optimizer's plan for the production 2pc family is pinned:
    deliver/timeout fuse into one switch class (timeout rows carry
    zero channel params, so the nondup decrement degenerates to
    identity on them), the trivial history elides its gather, no
    crash slots elide the crashed gating, and the step path holds to
    TWO table-row gathers (params + flat). The cache key carries the
    optimizer discriminator so naive and optimized programs never
    collide in the compile cache."""
    from stateright_tpu.models.two_phase_commit_actors import (
        two_phase_sys_compiled_encoded,
    )

    enc = two_phase_sys_compiled_encoded(5)
    plan = enc.codegen_opt
    assert plan["fused_switch"] is True
    assert plan["history_gather_elided"] is True
    assert plan["crash_gather_elided"] is True
    assert plan["step_gathers"] == 2
    # table dedup + column pruning really happened
    assert plan["flat_cols"][1] < plan["flat_cols"][0]
    assert plan["params_cols"][1] < plan["params_cols"][0]
    # every presence bit of the dup network + timers coalesced into
    # word-level runs: zero per-slot leftovers at this shape
    assert plan["mask_per_slot"] == 0
    assert plan["mask_bit_runs"] >= 1

    naive = two_phase_sys_compiled_encoded(5, optimize=False)
    assert naive.codegen_opt is None
    assert enc.cache_key() != naive.cache_key()
    assert "codegen-opt" in repr(enc.cache_key())


def test_registry_production_shape_entry():
    """The production-shape compiled pipeline is registered for the
    lint gates (analysis/registry.py): the rm=5 entry builds, caps
    its step path at 2 gathers, and the bench parity map names lanes
    that exist in the bench lane table."""
    from stateright_tpu.analysis.registry import get_encoding_spec

    spec = get_encoding_spec("compiled-2pc-sys-rm5")
    assert spec.kind == "compiled"
    assert spec.max_step_gathers == 2
    enc = spec.factory()
    assert enc.codegen_opt is not None
    assert enc.codegen_opt["step_gathers"] <= 2

    sys.path.insert(0, ".")
    try:
        from bench import COMPILED_PARITY, tpu_workloads
    finally:
        sys.path.pop(0)
    lanes = {name for name, *_ in tpu_workloads(quick=True)}
    for cname, hname in COMPILED_PARITY.items():
        assert cname in lanes, cname
        assert hname in lanes, hname
