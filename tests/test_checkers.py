"""Host checker engines: BFS, DFS, simulation, on-demand.

Pins implementation-independent ground truth from the reference test
suite (see BASELINE.md): LinearEquation full space = 65,536 unique
states (reference bfs.rs:443), eventually semantics on digraphs
(test_util.rs DGraph fixtures), and the documented revisit
false-negative (reference checker.rs:642-659).
"""

import io

import pytest

from stateright_tpu import (
    Expectation,
    Model,
    Path,
    PathRecorder,
    Property,
    StateRecorder,
    WriteReporter,
    fingerprint,
)
from stateright_tpu.fixtures import (
    BinaryClock,
    DGraph,
    LinearEquation,
    Panicker,
    PanickerError,
)


# -- BFS ----------------------------------------------------------------


def test_bfs_finds_solution():
    checker = LinearEquation(a=2, b=10, c=28).checker().spawn_bfs().join()
    path = checker.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (2 * x + 10 * y) % 256 == 28
    # BFS finds a shortest witness: x + y increments == depth-1.
    assert len(path) == x + y + 1


def test_bfs_full_space_when_unsolvable():
    # 2x + 4y is always even: full space explored, no discovery.
    # Unique count pinned at 256*256 (reference bfs.rs:436-444).
    checker = LinearEquation(a=2, b=4, c=33).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 65536
    assert checker.discovery("solvable") is None


def test_bfs_discovery_is_shortest_path():
    checker = LinearEquation(a=1, b=1, c=3).checker().spawn_bfs().join()
    path = checker.assert_any_discovery("solvable")
    assert len(path.actions()) == 3  # (0,3),(1,2),(2,1),(3,0) all depth 3


def test_bfs_always_counterexample():
    model = (
        DGraph.with_path([1, 2, 3])
        .property(Property.always("under 3", lambda m, s: s < 3))
    )
    checker = model.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("under 3")
    assert path.states() == [1, 2, 3]
    assert path.fingerprints() == [fingerprint(1), fingerprint(2), fingerprint(3)]


def test_bfs_eventually_satisfied():
    model = (
        DGraph.with_path([1, 2, 3])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    model.checker().spawn_bfs().join().assert_properties()


def test_bfs_eventually_counterexample_at_terminal():
    model = (
        DGraph.with_path([1, 2, 3])
        .path([1, 4])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    checker = model.checker().spawn_bfs().join()
    path = checker.assert_any_discovery("reaches 3")
    assert path.states() == [1, 4]


def test_bfs_eventually_revisit_false_negative():
    # Documented limitation reproduced from the reference
    # (checker.rs:642-659, bfs.rs:285-303): when a path re-joins an
    # already-visited state, its eventually-bits are dropped, missing
    # the counterexample via the second path.
    model = (
        DGraph.with_path([1, 2, 3])
        .path([4, 2])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    checker = model.checker().spawn_bfs().join()
    # State 4's path ends at visited state 2 whose bits were already
    # cleared on the 1->2->3 path; the 4->2 (then stuck... 2->3 exists)
    # actually reaches 3 — so no discovery, correctly. The false
    # negative needs 2 to be terminal-free; covered in the DFS variant.
    assert checker.discovery("reaches 3") is None


def test_bfs_threads_matches_sequential():
    """threads(n) runs a real worker pool (bfs.rs + job_market.rs
    work-share semantics): counts and the discovered property SET
    must match the sequential oracle exactly on a full-space run."""
    seq = LinearEquation(a=2, b=4, c=33).checker().spawn_bfs().join()
    par = (
        LinearEquation(a=2, b=4, c=33)
        .checker()
        .threads(4)
        .spawn_bfs()
        .join()
    )
    assert par.unique_state_count() == seq.unique_state_count() == 65536
    assert sorted(par.discoveries()) == sorted(seq.discoveries())


def test_bfs_threads_finds_discovery_and_replays():
    par = (
        LinearEquation(a=2, b=10, c=28)
        .checker()
        .threads(3)
        .spawn_bfs()
        .join()
    )
    path = par.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (2 * x + 10 * y) % 256 == 28


def test_bfs_threads_propagates_model_panic():
    with pytest.raises(PanickerError):
        Panicker().checker().threads(4).spawn_bfs().join()


def test_bfs_target_max_depth():
    checker = (
        LinearEquation(a=2, b=4, c=33)
        .checker()
        .target_max_depth(3)
        .spawn_bfs()
        .join()
    )
    assert checker.max_depth() == 3
    # Depth<=3 states of the inc-x/inc-y lattice: 1+2+3 = 6.
    assert checker.unique_state_count() == 6


def test_bfs_target_state_count():
    checker = (
        LinearEquation(a=2, b=4, c=33)
        .checker()
        .target_state_count(100)
        .spawn_bfs()
        .join()
    )
    assert 100 <= checker.unique_state_count() < 200


def test_bfs_visitor_records_states():
    recorder = StateRecorder()
    BinaryClock().checker().visitor(recorder).spawn_bfs().join()
    assert sorted(recorder.states) == [0, 1]


def test_bfs_path_recorder_paths_replayable():
    recorder = PathRecorder()
    model = DGraph.with_path([1, 2, 3]).path([1, 3])
    model.checker().visitor(recorder).spawn_bfs().join()
    assert {tuple(p.states()) for p in recorder.paths} == {
        (1,),
        (1, 2),
        (1, 3),
        (1, 2, 3),
    } or {tuple(p.states()) for p in recorder.paths} == {
        (1,),
        (1, 2),
        (1, 3),
    }


def test_bfs_propagates_model_errors():
    with pytest.raises(PanickerError):
        Panicker().checker().spawn_bfs().join()


def test_symmetry_rejected_on_bfs():
    with pytest.raises(ValueError):
        LinearEquation(1, 1, 1).checker().symmetry_fn(lambda s: s).spawn_bfs()


# -- DFS ----------------------------------------------------------------


def test_dfs_explores_full_space():
    checker = LinearEquation(a=2, b=4, c=33).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 65536
    assert checker.discovery("solvable") is None


def test_dfs_finds_solution_with_valid_path():
    checker = LinearEquation(a=2, b=10, c=28).checker().spawn_dfs().join()
    path = checker.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (2 * x + 10 * y) % 256 == 28
    # The fingerprint trace must replay: re-encode and re-decode.
    replayed = Path.from_fingerprints(checker.model, path.fingerprints())
    assert replayed.states() == path.states()


def test_dfs_eventually_counterexample():
    model = (
        DGraph.with_path([1, 2, 3])
        .path([1, 4])
        .property(Property.eventually("reaches 3", lambda m, s: s == 3))
    )
    checker = model.checker().spawn_dfs().join()
    path = checker.assert_any_discovery("reaches 3")
    assert path.states() == [1, 4]


def test_dfs_symmetry_reduces_but_paths_replay():
    # Mirror-symmetric lattice: representative sorts the pair, halving
    # the space; paths must continue from original states so they stay
    # replayable (reference dfs.rs:300-311, 484-510).
    model = LinearEquation(a=1, b=1, c=250)
    recorder = PathRecorder()
    checker = (
        model.checker()
        .symmetry_fn(lambda s: (min(s), max(s)))
        .visitor(recorder)
        .spawn_dfs()
        .join()
    )
    full = LinearEquation(a=1, b=1, c=250).checker().spawn_dfs().join()
    assert checker.unique_state_count() < full.unique_state_count()
    for p in recorder.paths:
        Path.from_fingerprints(model, p.fingerprints())  # raises if broken


# -- simulation ---------------------------------------------------------


def test_simulation_finds_example():
    checker = (
        LinearEquation(a=1, b=1, c=3)
        .checker()
        .target_state_count(50_000)
        .spawn_simulation(seed=0)
        .join()
    )
    path = checker.assert_any_discovery("solvable")
    x, y = path.last_state()
    assert (x + y) % 256 == 3


def test_simulation_is_deterministic_per_seed():
    def run(seed):
        return (
            LinearEquation(a=3, b=7, c=11)
            .checker()
            .target_state_count(5_000)
            .spawn_simulation(seed=seed)
            .join()
            .state_count()
        )

    assert run(7) == run(7)


def test_simulation_cycle_detection_terminates():
    # BinaryClock cycles 0->1->0; traces must end at the cycle.
    checker = (
        BinaryClock()
        .checker()
        .target_state_count(100)
        .spawn_simulation(seed=1)
        .join()
    )
    checker.assert_any_discovery("can be zero")


# -- on-demand ----------------------------------------------------------


def test_on_demand_expands_only_on_request():
    model = DGraph.with_path([1, 2, 3]).property(
        Property.sometimes("sees 3", lambda m, s: s == 3)
    )
    checker = model.checker().spawn_on_demand()
    assert checker.unique_state_count() == 1
    assert not checker.is_done()
    checker.check_fingerprint(fingerprint(1))
    assert checker.unique_state_count() == 2
    checker.check_fingerprint(fingerprint(2))
    assert checker.unique_state_count() == 3
    assert checker.discovery("sees 3") is None  # 3 not yet *evaluated*
    checker.check_fingerprint(fingerprint(3))
    checker.assert_any_discovery("sees 3")
    assert checker.is_done()


def test_on_demand_run_to_completion():
    model = DGraph.with_path([1, 2, 3]).property(
        Property.sometimes("sees 3", lambda m, s: s == 3)
    )
    checker = model.checker().spawn_on_demand()
    checker.run_to_completion()
    checker.assert_any_discovery("sees 3")
    assert checker.is_done()


# -- path / report ------------------------------------------------------


def test_path_encode_decode_roundtrip():
    model = DGraph.with_path([1, 2, 3])
    path = Path.from_fingerprints(
        model, [fingerprint(1), fingerprint(2), fingerprint(3)]
    )
    assert Path.decode(path.encode()) == path.fingerprints()
    assert path.actions() == [2, 3]
    assert path.last_state() == 3


def test_path_from_actions():
    model = LinearEquation(1, 1, 5)
    path = Path.from_actions(model, (0, 0), ["IncX", "IncY", "IncX"])
    assert path.last_state() == (2, 1)
    assert Path.from_actions(model, (0, 0), ["Bogus"]) is None


def test_write_reporter_format():
    out = io.StringIO()
    model = DGraph.with_path([1, 2]).property(
        Property.always("under 2", lambda m, s: s < 2)
    )
    model.checker().spawn_bfs().report(WriteReporter(out))
    text = out.getvalue()
    assert "Done. states=" in text
    assert "unique=" in text
    assert 'Discovered "under 2" counterexample' in text


def test_assert_properties_raises_on_violation():
    model = DGraph.with_path([1, 2]).property(
        Property.always("under 2", lambda m, s: s < 2)
    )
    checker = model.checker().spawn_bfs().join()
    with pytest.raises(AssertionError):
        checker.assert_properties()


def test_panic_cli_workload_propagates():
    """examples/panic.rs parity at the CLI surface: the panicking
    adder's error propagates cleanly out of the search."""
    import io
    from contextlib import redirect_stdout

    from stateright_tpu.cli import main

    buf = io.StringIO()
    with redirect_stdout(buf):
        main(["panic", "check"])
    assert "propagated the panic" in buf.getvalue()
