"""Property-based round-trips for the ops/bitmask.py word builders.

The packed-word layout (slot ``k`` in word ``k // 32`` at bit
``k % 32``, zero tail bits) is consumed by three independent parties —
the engines' popcount/peel pipeline, the hand encodings' class-mask
builders, and the compiled codegen's bit tables — so the builders are
pinned against brute-force references over randomized inputs (seeded
rng, many trials) rather than a handful of examples. ``K`` sweeps
deliberately include ``k % 32 == 0`` (the no-partial-tail-word edge:
``mask_words(64) == 2`` with every bit significant) alongside the
straddle cases.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.ops.bitmask import (  # noqa: E402
    bit_select,
    mask_to_words,
    mask_words,
    or_class_words,
    pack_bits_host,
    popcount_words,
    select_words_host,
    slot_mask_host,
    words_to_mask,
)

pytestmark = pytest.mark.lint

#: k % 32 == 0 cases first (tail word exactly full), then straddles.
KS = (32, 64, 96, 1, 17, 31, 33, 63, 65, 127, 200)


def _ref_pack(flags):
    words = [0] * max(1, (len(flags) + 31) // 32)
    for i, f in enumerate(flags):
        if f:
            words[i // 32] |= 1 << (i % 32)
    return tuple(words)


@pytest.mark.parametrize("k", KS)
def test_pack_bits_host_matches_reference_and_bit_select(k):
    rng = np.random.default_rng(k)
    for _ in range(20):
        flags = (rng.random(k) < rng.random()).tolist()
        words = pack_bits_host(flags)
        assert words == _ref_pack(flags)
        assert len(words) == max(1, mask_words(k))
        # every bit reads back through the traced selector
        idx = jnp.arange(k, dtype=jnp.uint32)
        got = np.asarray(
            jax.vmap(lambda i: bit_select(jnp, words, i))(idx)
        )
        assert (got == np.array(flags)).all()


def test_pack_bits_host_empty():
    assert pack_bits_host([]) == (0,)


@pytest.mark.parametrize("k", KS)
def test_mask_words_roundtrip_randomized(k):
    """mask -> words -> mask is the identity; popcount matches; tail
    bits beyond k are zero (words_to_mask would hide a dirty tail, so
    check the words directly)."""
    rng = np.random.default_rng(1000 + k)
    L = mask_words(k)
    for trial in range(20):
        density = rng.random()
        m = rng.random((7, k)) < density
        words = np.asarray(mask_to_words(jnp, jnp.asarray(m)))
        assert words.shape == (7, L)
        back = np.asarray(
            words_to_mask(jnp, jnp.asarray(words), k)
        )
        assert (back == m).all()
        cnt = np.asarray(popcount_words(jnp, jnp.asarray(words)))
        assert (cnt == m.sum(axis=1)).all()
        # tail-word hygiene: bits at positions >= k must be zero —
        # at k % 32 == 0 there ARE no tail bits (the edge case: every
        # bit of the last word is significant).
        tail_bits = L * 32 - k
        if tail_bits:
            assert (
                words[:, -1] >> np.uint32(32 - tail_bits) == 0
            ).all()
        else:
            # full last word must be reachable: force the top bit on
            m2 = m.copy()
            m2[:, k - 1] = True
            w2 = np.asarray(mask_to_words(jnp, jnp.asarray(m2)))
            assert (w2[:, -1] >> np.uint32(31) == 1).all()


@pytest.mark.parametrize("k", KS)
def test_slot_mask_host_is_indicator_pack(k):
    rng = np.random.default_rng(2000 + k)
    for _ in range(10):
        n_slots = int(rng.integers(0, min(k, 12) + 1))
        slots = sorted(
            rng.choice(k, size=n_slots, replace=False).tolist()
        )
        flags = [i in set(slots) for i in range(k)]
        assert slot_mask_host(k, slots) == _ref_pack(flags)
    with pytest.raises(ValueError):
        slot_mask_host(k, [k])
    with pytest.raises(ValueError):
        slot_mask_host(k, [-1])


@pytest.mark.parametrize("k", KS)
def test_or_class_words_matches_dense_or(k):
    """or_class_words under random traced conditions equals the dense
    OR reference, for host-tuple and array-valued classes alike —
    including the L == 1 scalar-word fast path and all-zero class
    dropping."""
    rng = np.random.default_rng(3000 + k)
    L = mask_words(k)
    classes_host = [
        sorted(
            rng.choice(
                k, size=int(rng.integers(0, min(k, 9) + 1)),
                replace=False,
            ).tolist()
        )
        for _ in range(5)
    ] + [[]]  # the all-zero class must drop for free
    masks = [slot_mask_host(k, cls) for cls in classes_host]

    def build(conds):
        return or_class_words(
            jnp,
            [(conds[i], masks[i]) for i in range(len(masks))],
            L,
        )

    for _ in range(10):
        conds = rng.random(len(masks)) < 0.5
        got = np.asarray(jax.jit(build)(jnp.asarray(conds)))
        assert got.shape == (L,)
        want = np.zeros(L, np.uint64)
        for on, m in zip(conds, masks):
            if on:
                want |= np.array(m, np.uint64)
        assert (got == want.astype(np.uint32)).all()
    # gather-free by construction
    jx = jax.make_jaxpr(build)(jnp.zeros(len(masks), bool))
    from stateright_tpu.analysis import is_gather, iter_eqns

    assert not any(
        is_gather(s.primitive) for s in iter_eqns(jx.jaxpr)
    )


@pytest.mark.parametrize("k", (32, 64, 17, 70))
def test_select_words_host_matches_indexing(k):
    rng = np.random.default_rng(4000 + k)
    L = mask_words(k)
    rows = [
        slot_mask_host(
            k,
            sorted(
                rng.choice(
                    k, size=int(rng.integers(1, min(k, 8) + 1)),
                    replace=False,
                ).tolist()
            ),
        )
        for _ in range(6)
    ]

    def sel(i):
        return select_words_host(jnp, rows, i)

    for v in range(len(rows)):
        got = np.asarray(jax.jit(sel)(jnp.uint32(v)))
        want = np.array(rows[v], np.uint32)
        if L == 1:
            # single-word rows select as scalars (const_words keeps
            # vmapped guard math [N]-shaped)
            assert got.shape == ()
            assert got == want[0]
        else:
            assert (got == want).all()
    # out-of-range picks rows[0] (the documented fallback)
    got = np.asarray(jax.jit(sel)(jnp.uint32(len(rows) + 3)))
    assert (
        np.atleast_1d(got) == np.array(rows[0], np.uint32)
    ).all()


def test_words_roundtrip_through_engine_convention():
    """words_to_mask(pack_bits_host(x)) == x for random x at the
    k % 32 == 0 edge — the host-pack and device-unpack conventions
    agree word for word."""
    rng = np.random.default_rng(7)
    for k in (32, 64, 96):
        flags = (rng.random(k) < 0.5).tolist()
        words = jnp.asarray(
            np.array(pack_bits_host(flags), np.uint32)
        )[None, :]
        back = np.asarray(words_to_mask(jnp, words, k))[0]
        assert (back == np.array(flags)).all()
